"""Per-request-class ready-task queues.

Reference: crates/tako/src/internal/scheduler/taskqueue.rs — one priority-
ordered queue per interned rq-id. Tasks enter when all dependencies finish and
leave when assigned to a worker. Priorities are (user_priority, scheduler
priority) pairs compared lexicographically, higher first.

Implementation: per queue, a dict priority -> deque plus a descending-sorted
key list maintained with bisect (distinct priorities are few). Cancelled tasks
are removed lazily via a tombstone set.
"""

from __future__ import annotations

from bisect import insort
from collections import deque

Priority = tuple[int, int]  # (user_priority, scheduler_priority), higher first


class TaskQueue:
    __slots__ = ("_levels", "_keys", "_tombstones", "_len")

    def __init__(self):
        self._levels: dict[Priority, deque[int]] = {}
        # _keys holds negated priorities so the list is ascending and
        # iteration order (descending priority) is a simple walk.
        self._keys: list[tuple[int, int]] = []
        self._tombstones: set[int] = set()
        self._len = 0

    def __len__(self) -> int:
        return self._len

    def add(self, priority: Priority, task_id: int) -> None:
        level = self._levels.get(priority)
        if level is None:
            level = deque()
            self._levels[priority] = level
            insort(self._keys, (-priority[0], -priority[1]))
        level.append(task_id)
        self._len += 1

    def remove(self, task_id: int) -> None:
        """Lazy removal (cancel / assignment elsewhere)."""
        self._tombstones.add(task_id)
        self._len -= 1

    def _compact_level(self, priority: Priority) -> deque[int]:
        level = self._levels[priority]
        if self._tombstones:
            kept = deque(t for t in level if t not in self._tombstones)
            self._tombstones.difference_update(set(level) - set(kept))
            self._levels[priority] = kept
            level = kept
        return level

    def priority_sizes(self) -> list[tuple[Priority, int]]:
        """Descending-priority [(priority, n_ready)], skipping empty levels."""
        out = []
        for nk in list(self._keys):
            priority = (-nk[0], -nk[1])
            level = self._compact_level(priority)
            if level:
                out.append((priority, len(level)))
            else:
                del self._levels[priority]
                self._keys.remove(nk)
        return out

    def take(self, priority: Priority, count: int) -> list[int]:
        """Pop up to `count` tasks at the given priority level (FIFO)."""
        if priority not in self._levels:
            return []
        level = self._compact_level(priority)
        taken = []
        while level and len(taken) < count:
            taken.append(level.popleft())
        self._len -= len(taken)
        if not level:
            del self._levels[priority]
            self._keys.remove((-priority[0], -priority[1]))
        return taken

    def all_tasks(self) -> list[int]:
        out = []
        for priority in list(self._levels):
            out.extend(self._compact_level(priority))
        return out


class TaskQueues:
    """rq-id -> TaskQueue, plus bookkeeping of total ready tasks.

    Queues come from utils.native.make_task_queue: the C++ implementation
    (native/hqcore.cpp) when available, else the Python TaskQueue above —
    identical interfaces and semantics (tests/test_native.py pins parity).
    """

    def __init__(self):
        self._queues: dict[int, TaskQueue] = {}
        # monotone mutation counter over add/remove (takes during mapping/
        # prefill are reactor-internal and show up in total_ready instead):
        # the pipelined tick uses (membership, version, total_ready) as a
        # cheap "could a re-solve see different inputs?" signature
        self.version = 0

    def queue(self, rq_id: int) -> TaskQueue:
        q = self._queues.get(rq_id)
        if q is None:
            from hyperqueue_tpu.utils.native import make_task_queue

            q = make_task_queue()
            self._queues[rq_id] = q
        return q

    def add(self, rq_id: int, priority: Priority, task_id: int) -> None:
        self.version += 1
        self.queue(rq_id).add(priority, task_id)

    def remove(self, rq_id: int, task_id: int) -> None:
        q = self._queues.get(rq_id)
        if q is not None:
            self.version += 1
            q.remove(task_id)

    def items(self):
        return [(rq_id, q) for rq_id, q in self._queues.items() if len(q)]

    def total_ready(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def sanity_check(self) -> None:
        for q in self._queues.values():
            n = sum(count for _, count in q.priority_sizes())
            assert n == len(q), "queue length bookkeeping broken"
