"""Per-request-class ready-task queues.

Reference: crates/tako/src/internal/scheduler/taskqueue.rs — one priority-
ordered queue per interned rq-id. Tasks enter when all dependencies finish and
leave when assigned to a worker. Priorities are (user_priority, scheduler
priority) pairs compared lexicographically, higher first.

Implementation: per queue, a dict priority -> deque plus a descending-sorted
key list maintained with bisect (distinct priorities are few). Cancelled tasks
are removed lazily via a tombstone set.
"""

from __future__ import annotations

from bisect import insort
from collections import deque

Priority = tuple[int, int]  # (user_priority, scheduler_priority), higher first

# --- scheduler-priority encoding -------------------------------------------
#
# The scheduler component packs (job_id, b-level) into one int so the
# existing lexicographic (user, sched) comparison yields: older job first
# (reference -job_id FIFO), and WITHIN a job deeper critical path first
# (b-level lookahead — a task with more dependent work below it outranks its
# siblings). Encoding: sched = -(job_id * BLEVEL_STRIDE + BLEVEL_MAX - blevel)
# so cross-job ordering stays strict (any blevel of job J beats every blevel
# of job J+1) while higher blevel yields a higher (less negative) value
# within the job. Values with magnitude < BLEVEL_STRIDE are legacy raw
# literals (tests pass -job_id directly) and decode as (job=-sched, blevel=0).

BLEVEL_STRIDE = 1 << 20
BLEVEL_MAX = 1 << 16


def encode_sched_priority(job_id: int, blevel: int = 0) -> int:
    if blevel > BLEVEL_MAX:
        blevel = BLEVEL_MAX
    elif blevel < 0:
        blevel = 0
    return -(job_id * BLEVEL_STRIDE + BLEVEL_MAX - blevel)


def decode_sched_job(sched: int) -> int:
    p = -sched
    if p < BLEVEL_STRIDE:
        return p  # legacy raw -job_id literal
    return p // BLEVEL_STRIDE


def decode_sched_blevel(sched: int) -> int:
    p = -sched
    if p < BLEVEL_STRIDE:
        return 0
    return BLEVEL_MAX - (p % BLEVEL_STRIDE)


class TaskQueue:
    __slots__ = ("_levels", "_keys", "_tombstones", "_len")

    def __init__(self):
        self._levels: dict[Priority, deque[int]] = {}
        # _keys holds negated priorities so the list is ascending and
        # iteration order (descending priority) is a simple walk.
        self._keys: list[tuple[int, int]] = []
        self._tombstones: set[int] = set()
        self._len = 0

    def __len__(self) -> int:
        return self._len

    def add(self, priority: Priority, task_id: int) -> None:
        level = self._levels.get(priority)
        if level is None:
            level = deque()
            self._levels[priority] = level
            insort(self._keys, (-priority[0], -priority[1]))
        level.append(task_id)
        self._len += 1

    def remove(self, task_id: int) -> None:
        """Lazy removal (cancel / assignment elsewhere)."""
        self._tombstones.add(task_id)
        self._len -= 1

    def _compact_level(self, priority: Priority) -> deque[int]:
        level = self._levels[priority]
        if self._tombstones:
            kept = deque(t for t in level if t not in self._tombstones)
            self._tombstones.difference_update(set(level) - set(kept))
            self._levels[priority] = kept
            level = kept
        return level

    def priority_sizes(self) -> list[tuple[Priority, int]]:
        """Descending-priority [(priority, n_ready)], skipping empty levels."""
        out = []
        for nk in list(self._keys):
            priority = (-nk[0], -nk[1])
            level = self._compact_level(priority)
            if level:
                out.append((priority, len(level)))
            else:
                del self._levels[priority]
                self._keys.remove(nk)
        return out

    def take(self, priority: Priority, count: int) -> list[int]:
        """Pop up to `count` tasks at the given priority level (FIFO)."""
        if priority not in self._levels:
            return []
        level = self._compact_level(priority)
        taken = []
        while level and len(taken) < count:
            taken.append(level.popleft())
        self._len -= len(taken)
        if not level:
            del self._levels[priority]
            self._keys.remove((-priority[0], -priority[1]))
        return taken

    def all_tasks(self) -> list[int]:
        out = []
        for priority in list(self._levels):
            out.extend(self._compact_level(priority))
        return out


class LazyQueueView:
    """One rq class's queue with its lazy array segments merged in.

    Returned by TaskQueues.queue()/items() only while the class has
    unmaterialized lazy tasks (server/lazy.py): counts include them, and
    `take` materializes ids on demand AFTER draining the base queue (so
    requeued/materialized tasks keep approximate FIFO precedence at equal
    priority). The view deliberately does NOT subclass the native queue —
    tick mapping falls back to its per-cell Python take path whenever a
    batch queue is a view, which is where materialization hooks in.

    `all_tasks`/`remove` cover the BASE queue only: whole-job operations
    on lazy tasks go through LazyStore.detach_job/materialize_job first.
    """

    __slots__ = ("_base", "_store", "_core", "_rq_id")

    def __init__(self, base, store, core, rq_id: int):
        self._base = base
        self._store = store
        self._core = core
        self._rq_id = rq_id

    def __len__(self) -> int:
        return len(self._base) + self._store.ready_count_rq(self._rq_id)

    def priority_sizes(self) -> list[tuple[Priority, int]]:
        lazy = self._store.level_sizes(self._rq_id)
        merged: dict[Priority, int] = dict(self._base.priority_sizes())
        for priority, n in lazy.items():
            merged[priority] = merged.get(priority, 0) + n
        return sorted(merged.items(), key=lambda kv: kv[0], reverse=True)

    def take(self, priority: Priority, count: int) -> list[int]:
        got = self._base.take(priority, count)
        if len(got) < count:
            got.extend(
                self._store.take(
                    self._core, self._rq_id, priority, count - len(got)
                )
            )
        return got

    def add(self, priority: Priority, task_id: int) -> None:
        self._base.add(priority, task_id)

    def remove(self, task_id: int) -> None:
        self._base.remove(task_id)

    def all_tasks(self) -> list[int]:
        return self._base.all_tasks()


class TaskQueues:
    """rq-id -> TaskQueue, plus bookkeeping of total ready tasks.

    Queues come from utils.native.make_task_queue: the C++ implementation
    (native/hqcore.cpp) when available, else the Python TaskQueue above —
    identical interfaces and semantics (tests/test_native.py pins parity).

    When a lazy-array store is bound (Core.__post_init__ links
    server/lazy.LazyStore), classes holding unmaterialized array tasks are
    served through LazyQueueView so batch sizing and takes transparently
    include them; classes without lazy tasks keep the bare (native) queue
    and its one-call map-take fast path.
    """

    def __init__(self):
        self._queues: dict[int, TaskQueue] = {}
        # monotone mutation counter over add/remove (takes during mapping/
        # prefill are reactor-internal and show up in total_ready instead):
        # the pipelined tick uses (membership, version, total_ready) as a
        # cheap "could a re-solve see different inputs?" signature
        self.version = 0
        # bound by Core.__post_init__; None for standalone queue users
        self.lazy = None
        self._core = None

    def bind_lazy(self, store, core) -> None:
        self.lazy = store
        self._core = core

    def _base(self, rq_id: int) -> TaskQueue:
        q = self._queues.get(rq_id)
        if q is None:
            from hyperqueue_tpu.utils.native import make_task_queue

            q = make_task_queue()
            self._queues[rq_id] = q
        return q

    def queue(self, rq_id: int):
        q = self._base(rq_id)
        if self.lazy is not None and self.lazy.ready_count_rq(rq_id) > 0:
            return LazyQueueView(q, self.lazy, self._core, rq_id)
        return q

    def add(self, rq_id: int, priority: Priority, task_id: int) -> None:
        self.version += 1
        self._base(rq_id).add(priority, task_id)

    def remove(self, rq_id: int, task_id: int) -> None:
        q = self._queues.get(rq_id)
        if q is not None:
            self.version += 1
            q.remove(task_id)

    def items(self):
        lazy_rqs = (
            set(self.lazy.ready_rqs()) if self.lazy is not None else set()
        )
        out = [
            (rq_id, self.queue(rq_id) if rq_id in lazy_rqs else q)
            for rq_id, q in self._queues.items()
            if len(q) or rq_id in lazy_rqs
        ]
        for rq_id in lazy_rqs:
            if rq_id not in self._queues:
                out.append((rq_id, self.queue(rq_id)))
        return out

    def total_ready(self) -> int:
        n = sum(len(q) for q in self._queues.values())
        if self.lazy is not None:
            n += self.lazy.ready
        return n

    def sanity_check(self) -> None:
        for q in self._queues.values():
            n = sum(count for _, count in q.priority_sizes())
            assert n == len(q), "queue length bookkeeping broken"
