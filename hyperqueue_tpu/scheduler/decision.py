"""Scheduling-decision vocabulary: reason codes + per-tick DecisionRecords.

The MILP/greedy solve is only operable when every outcome is attributable
to an input (Gavel, arXiv:2008.09213 §5; JASDA, arXiv:2510.14599): a task
left pending must name WHICH constraint held it back, not just "not
scheduled".  This module is the single registry of reason codes — every
code the scheduler can emit is a ``REASON_*`` constant here, and the docs
checker (tests/test_explain.py) asserts each one is listed in the
docs/observability.md catalog, so a code cannot ship undocumented.

Classification runs once per tick over the LEFTOVER batches only (classes
the solve did not drain), never per task: tasks of one request class share
one reason, so the cost is O(leftover classes x workers) against the ≤5%
tick-budget guard (ISSUE 4 acceptance; watched by ``bench.py --phases``).
"""

from __future__ import annotations

from hyperqueue_tpu.resources.request import AllocationPolicy
from hyperqueue_tpu.scheduler.queues import (
    decode_sched_blevel,
    decode_sched_job,
)

# --- reason codes (the registry; keep docs/observability.md in sync) ------
# No connected worker could EVER run the task (resource totals too small,
# or the resource name is not provided anywhere).
REASON_NO_MATCHING_WORKER = "no-matching-worker"
# Capable workers exist, but everything they have is currently occupied.
REASON_INSUFFICIENT_CAPACITY = "insufficient-capacity"
# A multi-node gang is waiting for enough idle same-group workers.
REASON_GANG_INCOMPLETE = "gang-incomplete"
# The task's job is paused (`hq job pause`); it is held out of the queues.
REASON_QUEUE_PAUSED = "queue-paused"
# Capacity was free this tick but the solver deliberately left the class
# unplaced (priority interleaving, cut cap, gang reservation drain).
REASON_SOLVER_DEFERRED = "solver-deferred"
# Same as solver-deferred, but the tick ran on the watchdog's host-greedy
# fallback after the primary solver failed or timed out.
REASON_WATCHDOG_FALLBACK = "watchdog-fallback"
# Capable workers exist but none has enough remaining lifetime for the
# request's min_time (--time-request vs worker --time-limit).
REASON_WORKER_LIFETIME = "worker-lifetime"
# The task still waits on unfinished dependencies (not in any queue yet).
REASON_WAITING_DEPS = "waiting-dependencies"
# Marker entry: a pathological tick had more unplaced classes than a
# DecisionRecord keeps (MAX_UNPLACED_ENTRIES); the count is the folded tail.
REASON_TRUNCATED = "truncated"
# A multi-node gang had enough capable workers overall, but the fused solve
# found no single group with enough members free this tick (or in-solve
# holdback kept members idle for it) — the gang retries next tick.
REASON_GANG_GROUP_DEFERRED = "gang-group-deferred"
# The solve placed deeper critical-path work of the SAME job first (b-level
# lookahead); this class was deliberately held behind it this tick.
REASON_LOOKAHEAD_HELD = "lookahead-held"
# A fairness-boosted job (scheduler/policy.py dominant-resource deficit)
# jumped ahead of this class's original priority this tick; the class waits
# while the under-served job catches up to its fair share.
REASON_FAIRNESS_DEFERRED = "fairness-deferred"

ALL_REASONS = frozenset(
    value
    for name, value in globals().items()
    if name.startswith("REASON_")
)


def format_reason_counts(reasons: dict) -> str:
    """"30 insufficient-capacity, 7 gang-incomplete" — descending count.

    The one formatter for per-job pending-reason summaries, shared by
    `hq job info` and the dashboard so the two cannot drift.
    """
    return ", ".join(
        f"{n} {code}"
        for code, n in sorted(reasons.items(), key=lambda kv: -kv[1])
    )


def _lifetime_scan(core, rqv) -> tuple[bool, bool]:
    """(lifetime_ok, stable): can some amount-capable worker's REMAINING
    lifetime cover a variant's min_time — and is that verdict stable
    within the current membership epoch?  Lifetimes only shrink, so a
    False verdict is stable, and a True verdict is stable iff it is backed
    by an unlimited-lifetime worker or a zero-min_time variant; a True
    backed only by finite-lifetime workers must be re-checked each call.
    """
    ok = False
    for w in core.workers.values():
        for v in rqv.variants:
            if not w.resources.is_capable_of(v):
                continue
            if v.min_time_secs <= w.lifetime_secs():
                ok = True
                if (
                    v.min_time_secs <= 0
                    or w.configuration.time_limit_secs <= 0
                ):
                    return True, True
    return ok, not ok


def variant_fits_free(worker, variant, n_r: int | None = None) -> bool:
    """Can ONE task of `variant` start on `worker` RIGHT NOW (free-based)?

    Mirrors the solver's per-worker capacity test (oracle.solve_oracle caps):
    free amounts, the nt_free task slot, remaining lifetime vs min_time, and
    the ALL-policy idle-pool requirement.
    """
    if worker.nt_free <= 0:
        return False
    if variant.min_time_secs > worker.lifetime_secs():
        return False
    free = worker.free
    for entry in variant.entries:
        rid = entry.resource_id
        have = free[rid] if rid < len(free) else 0
        if entry.policy is AllocationPolicy.ALL:
            total = worker.resources.amount(rid)
            if total <= 0 or have != total:
                return False
        elif have < entry.amount:
            return False
    return True


def classify_class(
    core, rq_id: int, rqv=None, degraded: bool = False,
    check_free: bool = True,
) -> str:
    """Reason code for a request class the tick left unplaced.

    Decision ladder (most fundamental constraint wins):

    1. no worker's TOTAL resources could ever host any variant
       -> no-matching-worker
    2. amounts fit somewhere, but no such worker's remaining lifetime
       covers the variant's min_time -> worker-lifetime
    3. no worker could take one task from its FREE resources right now
       -> insufficient-capacity
    4. free capacity existed but the solve left the class anyway
       -> watchdog-fallback on a degraded tick, else solver-deferred

    Steps 1-2 are pure in (class, worker set): memoized on
    ``core.capable_memo`` keyed by the membership epoch, so steady-state
    ticks pay two dict lookups.  Step 3's free scan is per-tick by nature;
    ``check_free=False`` skips it (the per-tick path drops it past a
    budget, see build_unplaced_entries) — the solve already proved nothing
    fit, so the answer collapses to insufficient-capacity (or
    watchdog-fallback on a degraded tick, where the fallback's judgment is
    not the primary solver's).
    """
    if rqv is None:
        rqv = core.rq_map.get_variants(rq_id)
    cached = core.capable_memo.get(rq_id)
    if cached is None or cached[0] != core.membership_epoch:
        amount_capable = any(
            w.resources.is_capable_of_rqv(rqv)
            for w in core.workers.values()
        )
        lifetime_ok, stable = (
            _lifetime_scan(core, rqv) if amount_capable else (False, True)
        )
        cached = (core.membership_epoch, amount_capable, lifetime_ok, stable)
        core.capable_memo[rq_id] = cached
    _, amount_capable, lifetime_ok, stable = cached
    if amount_capable and not stable:
        # lifetime_ok was satisfied only by finite-lifetime workers, and
        # remaining lifetimes decay within an epoch — recompute (a False
        # verdict, or one backed by an unlimited worker, cannot change
        # until membership does, so those stay cached)
        lifetime_ok, stable = _lifetime_scan(core, rqv)
        if stable:
            core.capable_memo[rq_id] = (
                core.membership_epoch, amount_capable, lifetime_ok, True
            )
    if not amount_capable:
        return REASON_NO_MATCHING_WORKER
    if not lifetime_ok:
        return REASON_WORKER_LIFETIME
    if check_free:
        for w in core.workers.values():
            if w.mn_task or w.mn_reserved:
                continue  # carved out of the solve this tick
            if not w.resources.is_capable_of_rqv(rqv):
                continue
            if any(variant_fits_free(w, v) for v in rqv.variants):
                return (
                    REASON_WATCHDOG_FALLBACK if degraded
                    else REASON_SOLVER_DEFERRED
                )
        return REASON_INSUFFICIENT_CAPACITY
    return (
        REASON_WATCHDOG_FALLBACK if degraded
        else REASON_INSUFFICIENT_CAPACITY
    )


# unplaced entries kept per DecisionRecord; the tail is folded into a
# truncation marker so a pathological tick cannot bloat the flight ring
MAX_UNPLACED_ENTRIES = 64
# skip the per-worker free scan when classes x workers exceeds this: the
# scan only separates solver-deferred from insufficient-capacity, and at
# scale the solve's own verdict (nothing fit) is trusted instead — keeps
# decision recording inside the <=5% tick budget at 1k workers
FREE_SCAN_BUDGET = 20_000


def build_unplaced_entries(
    core, leftover_batches, rq_reasons, degraded: bool = False,
    placed_blevel: dict | None = None,
    fairness_placed: tuple | None = None,
) -> list[dict]:
    """Fold leftover batches into per-(class, job) unplaced entries.

    `rq_reasons` memoizes classify_class per rq_id for this tick.  Job
    attribution uses the scheduler priority component: the jobs layer
    submits every task with priority=(user, encode_sched_priority(job_id,
    blevel)) — see scheduler/queues.py — so one batch always belongs to
    exactly one job — EXCEPT the per-queue tail batch that create_batches
    folds past MAX_CUTS_PER_QUEUE, whose merged tasks are all charged to
    the tail batch's job (a known approximation at > 32 distinct priority
    levels per class; `hq task explain` still answers correctly for the
    other jobs via live classification).

    `placed_blevel` maps job_id -> max decoded b-level among batches that
    DID receive assignments this tick; a solver-deferred class whose own
    b-level is strictly below that mark was held behind deeper
    critical-path work of its own job and reports lookahead-held instead.

    `fairness_placed` is the LOWEST original priority tuple among batches
    of fairness-boosted jobs that received assignments this tick (None when
    no boosted job placed work): a still-solver-deferred class whose own
    original priority is strictly ABOVE that mark was overtaken by the
    fairness boost and reports fairness-deferred instead.
    """
    entries: list[dict] = []
    truncated = 0
    leftover_classes = {
        b.rq_id for b in leftover_batches if b.size > 0
    }
    check_free = (
        len(leftover_classes) * len(core.workers) <= FREE_SCAN_BUDGET
    )
    for batch in leftover_batches:
        if batch.size <= 0:
            continue
        if len(entries) >= MAX_UNPLACED_ENTRIES:
            truncated += batch.size
            continue
        reason = rq_reasons.get(batch.rq_id)
        if reason is None:
            reason = rq_reasons[batch.rq_id] = classify_class(
                core, batch.rq_id, degraded=degraded,
                check_free=check_free,
            )
        job_id = decode_sched_job(batch.priority[1])
        if placed_blevel and reason == REASON_SOLVER_DEFERRED:
            placed = placed_blevel.get(job_id)
            if (
                placed is not None
                and decode_sched_blevel(batch.priority[1]) < placed
            ):
                reason = REASON_LOOKAHEAD_HELD
        if (
            fairness_placed is not None
            and reason == REASON_SOLVER_DEFERRED
            and tuple(batch.priority) > tuple(fairness_placed)
        ):
            reason = REASON_FAIRNESS_DEFERRED
        entries.append({
            "rq_id": batch.rq_id,
            "job": job_id,
            "priority": batch.priority[0],
            "count": batch.size,
            "reason": reason,
        })
    if truncated:
        entries.append({
            "rq_id": None, "job": None, "priority": None,
            "count": truncated, "reason": REASON_TRUNCATED,
        })
    return entries
