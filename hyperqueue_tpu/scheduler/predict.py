"""Per-task-class runtime prediction for the weighted scheduling objective.

Value-function scheduling (arxiv 2011.14486) weights the objective with
runtime predictions mined from history instead of gating on them: the
predictor here keeps one exponentially-weighted moving average of observed
task runtimes per task class (the job name — array jobs share a name, so a
class accumulates across every sibling task), and the policy layer
(scheduler/policy.py) folds the expected remaining work into the priority
encoding as a bounded LPT boost — deep DAGs and straggler tails schedule by
predicted critical path, not arrival order.

Two feeds, same table:

* LIVE: the server's EventBridge observes every task-finished/task-failed
  runtime as it commits (server/bootstrap.py), so the EWMA tracks the
  cluster while it runs.
* OFFLINE: `seed_from_journal` replays a PR 14 journal (events/journal.py
  Journal.read_all) and pairs each task-started record's `started_at` stamp
  with its task-finished commit time, so a fresh server (or a simulator A/B
  run) starts with the previous run's learned runtimes instead of a cold
  table.

The predictor is deliberately tiny and deterministic: a dict of floats
folded in event order. Both feeds produce identical tables for identical
event streams, which the simulator's determinism contract relies on.
"""

from __future__ import annotations


class RuntimePredictor:
    """EWMA runtime table keyed by task class (job name).

    hit-rate telemetry: `predict` counts how often a lookup had data —
    `hq server stats` surfaces it so an operator can see whether the
    prediction term is actually informed or still cold.
    """

    def __init__(self, alpha: float = 0.3):
        self.alpha = float(alpha)
        self._ewma: dict[str, float] = {}
        self._counts: dict[str, int] = {}
        self._hits = 0
        self._misses = 0
        self.seeded_from: str | None = None
        self.seeded_samples = 0

    def observe(self, label: str, runtime_s: float) -> None:
        if not label or runtime_s is None or runtime_s < 0:
            return
        runtime_s = float(runtime_s)
        prev = self._ewma.get(label)
        if prev is None:
            self._ewma[label] = runtime_s
        else:
            self._ewma[label] = prev + self.alpha * (runtime_s - prev)
        self._counts[label] = self._counts.get(label, 0) + 1

    def predict(self, label: str) -> float | None:
        val = self._ewma.get(label)
        if val is None:
            self._misses += 1
        else:
            self._hits += 1
        return val

    def peek(self, label: str) -> float | None:
        """`predict` without touching the hit-rate counters (stats paths)."""
        return self._ewma.get(label)

    def hit_rate(self) -> float:
        asked = self._hits + self._misses
        return (self._hits / asked) if asked else 0.0

    def n_classes(self) -> int:
        return len(self._ewma)

    def stats(self) -> dict:
        out = {
            "classes": self.n_classes(),
            "observations": sum(self._counts.values()),
            "hit_rate": round(self.hit_rate(), 4),
        }
        if self.seeded_from is not None:
            out["seeded_from"] = self.seeded_from
            out["seeded_samples"] = self.seeded_samples
        return out

    def seed_from_journal(self, path: str) -> int:
        """Replay a journal offline and fold every completed task's runtime
        into the table. Returns the number of samples folded.

        Pairing: `job-submitted` maps job id -> class label (desc name);
        `task-started` stamps (job, task) with its `started_at`;
        `task-finished` closes the pair at the record's commit time. The
        worker-side trace stamps (spawned/exited) are preferred when both
        ride the finish record — they exclude the uplink/commit latency.
        Unpaired or malformed records are skipped, not fatal: a salvaged
        journal tail must not kill policy loading.
        """
        from hyperqueue_tpu.events.journal import Journal

        names: dict[int, str] = {}
        started: dict[tuple[int, int], float] = {}
        folded = 0
        for rec in Journal.read_all(path, salvage=True):
            kind = rec.get("event")
            if kind == "job-submitted":
                desc = rec.get("desc") or {}
                names[rec.get("job")] = desc.get("name", "job")
            elif kind == "task-started":
                key = (rec.get("job"), rec.get("task"))
                started[key] = rec.get("started_at") or rec.get("time", 0.0)
            elif kind == "task-finished":
                key = (rec.get("job"), rec.get("task"))
                t0 = started.pop(key, None)
                label = names.get(rec.get("job"))
                trace = rec.get("trace") or {}
                spawned = trace.get("spawned_at")
                exited = trace.get("exited_at")
                if spawned and exited and exited >= spawned:
                    runtime = exited - spawned
                elif t0 is not None:
                    runtime = rec.get("time", 0.0) - t0
                else:
                    continue
                if label and runtime >= 0:
                    self.observe(label, runtime)
                    folded += 1
        self.seeded_from = str(path)
        self.seeded_samples += folded
        return folded
