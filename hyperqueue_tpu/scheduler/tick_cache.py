"""Persistent tick-state cache: the dense snapshot survives across ticks.

Before this cache, every `reactor.schedule()` re-materialized the whole
dense solver state from Python dicts: `core.worker_rows()` rebuilt all
`WorkerRow`s, and `assemble_solve_inputs` re-allocated and re-filled the
`free`/`total`/`nt_free`/`lifetime` arrays from scratch.  At the 1M x 1k
north-star shape that host bookkeeping — not the solve — dominated the
tick (BASELINE.json; same lesson as Gavel's round-based policy engine:
the reallocation round must be far cheaper than the work it places).

`TickStateCache` keeps the `(W, R)` matrices and `(W,)` vectors alive and
applies dirty-tracking deltas instead of rebuilding:

- every `Worker.assign`/`unassign` bumps the worker's `epoch`
  (server/worker.py) — the ONE funnel for free/nt_free mutation;
- `sync()` walks the eligible workers once, rewrites only rows whose
  epoch moved, and refreshes lifetimes for time-limited workers;
- membership changes (connect/disconnect, gang reservation flips) and
  resource-map widening are structural: the row map is rebuilt and the
  `full_rebuilds` counter increments — steady-state ticks must keep it
  at zero (pinned by bench.py --smoke and tests/test_tick_cache.py).

Correctness contract: an incremental assemble must be BIT-IDENTICAL to a
from-scratch assemble of the same state.  `paranoid_check` runs both
paths and asserts array equality; the server exposes it as
`hq server start --paranoid-tick N` and the randomized golden test
(tests/test_tick_cache.py) drives ~hundreds of mutation steps through it.

The cache deliberately disables itself (sync() returns None) while any
eligible worker carries a min-utilization floor: floored workers move in
and out of the dense row set per tick (run_tick's carve-out), so their
presence makes membership time-dependent — and they are rare, autoalloc
-spawned workers.  The legacy from-scratch path remains for that case.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass(slots=True)
class DenseSnapshot:
    """One tick's dense worker-side state, aligned row-for-row.

    Arrays are OWNED by the cache and reused next tick: consumers must
    treat them as read-only (assemble_solve_inputs copies before any
    range-compression shift).
    """

    worker_ids: list[int]        # row -> worker_id, solve order
    free: np.ndarray             # (W, R) int64, uncompressed fractions
    total: np.ndarray            # (W, R) int64 pool totals
    nt_free: np.ndarray          # (W,) int32, clamped >= 0
    lifetime: np.ndarray         # (W,) int32 seconds


@dataclass
class TickPhaseStats:
    """Per-phase tick latency breakdown, recorded by the reactor.

    Mirrors the phases of one schedule(): batches -> assemble ->
    solve-dispatch -> device-sync -> mapping (plus gangs/prefill, traced
    separately).  Surfaced through `hq server stats` and bench.py
    --phases so a latency regression names its phase instead of one
    opaque number.
    """

    ticks: int = 0
    totals_ms: dict = field(default_factory=dict)   # phase -> cumulative ms
    last_ms: dict = field(default_factory=dict)     # phase -> last tick ms
    max_ms: dict = field(default_factory=dict)      # phase -> max ms

    def record(self, phases: dict) -> None:
        self.ticks += 1
        for name, ms in phases.items():
            self.totals_ms[name] = self.totals_ms.get(name, 0.0) + ms
            self.last_ms[name] = ms
            if ms > self.max_ms.get(name, 0.0):
                self.max_ms[name] = ms

    def snapshot(self) -> dict:
        out = {
            "ticks": self.ticks,
            "phases": {
                name: {
                    "total_ms": round(total, 3),
                    "mean_ms": round(total / max(self.ticks, 1), 4),
                    "last_ms": round(self.last_ms.get(name, 0.0), 4),
                    "max_ms": round(self.max_ms.get(name, 0.0), 4),
                }
                for name, total in sorted(self.totals_ms.items())
            },
        }
        return out

    def shares(self) -> dict:
        """Phase -> fraction of total tick time (all phases sum to ~1.0).

        The regression-blame side of the profiling plane (ISSUE 19):
        bench smokes store these next to the profiler's per-plane CPU
        shares, and ``--regress`` diffs both against the prior-row
        median so a latency regression names the phase whose share grew
        rather than one opaque wall-clock number."""
        total = sum(self.totals_ms.values())
        if total <= 0:
            return {}
        return {
            name: round(t / total, 4)
            for name, t in sorted(self.totals_ms.items())
        }


class TickStateCache:
    """Dirty-tracked dense snapshot of the schedulable workers."""

    def __init__(self) -> None:
        self.worker_ids: list[int] = []
        self._workers: list = []          # same order as worker_ids
        self._epochs: list[int] = []
        self._timed_rows: list[int] = []  # rows with a finite time limit
        # (core.membership_epoch, n_r) of the last sync: when unchanged,
        # the O(W) membership walk is skipped entirely and only row
        # CONTENT (Worker.epoch) is scanned
        self._sync_ver: tuple | None = None
        self._mu_blocked = False
        self.n_r = 0
        self.free: np.ndarray | None = None
        self.total: np.ndarray | None = None
        self.nt_free: np.ndarray | None = None
        self.lifetime: np.ndarray | None = None
        # telemetry (exposed via server stats / bench --phases)
        self.full_rebuilds = 0
        self.incremental_syncs = 0
        self.rows_rewritten_last = 0
        # sort-key memo for assemble_solve_inputs: the (scarcity,
        # objective) keys are pure per rq class + per-tick free totals;
        # totals are often unchanged tick-over-tick (e.g. release then
        # re-assign), so the whole per-class key dict is reusable
        self.sort_key_sig: tuple | None = None
        self.sort_keys: dict = {}
        # batch-layout memo: needs/min_time/all_mask/weights are pure in
        # the sorted rq-id sequence (+ dims), which steady ticks repeat
        self.batch_layout_sig: tuple | None = None
        self.batch_layout: dict | None = None

    # ------------------------------------------------------------------
    def sync(self, core) -> DenseSnapshot | None:
        """Bring the dense arrays up to date with `core`; returns the
        snapshot, or None when the cache cannot serve this tick (a
        min-utilization worker is present — see module docstring)."""
        n_r = len(core.resource_map)
        ver = (core.membership_epoch, n_r)
        if self.free is not None and ver == self._sync_ver:
            # common steady-state tick: membership and map width unchanged
            # since last sync — only row content can have moved
            if self._mu_blocked or not self.worker_ids:
                return None
            self._refresh_dirty()
            return self._snapshot()

        eligible = []
        mu_blocked = False
        for w in core.workers.values():
            if w.mn_task != 0 or w.mn_reserved != 0 or w.draining:
                continue
            if w.configuration.min_utilization > 0.001:
                mu_blocked = True
                break
            eligible.append(w)
        self._sync_ver = ver
        self._mu_blocked = mu_blocked
        if mu_blocked:
            return None
        ids = [w.worker_id for w in eligible]
        if self.free is None or ids != self.worker_ids:
            self._rebuild(eligible, n_r)
        else:
            # same rows, same order (worker ids never recycle, so equal
            # ids means the same Worker objects): a pure width change
            # and/or content drift
            if n_r != self.n_r:
                self._widen(n_r)
            self._refresh_dirty()
        if not ids:
            return None
        return self._snapshot()

    def _snapshot(self) -> DenseSnapshot:
        return DenseSnapshot(
            worker_ids=self.worker_ids,
            free=self.free,
            total=self.total,
            nt_free=self.nt_free,
            lifetime=self.lifetime,
        )

    # ------------------------------------------------------------------
    def _rebuild(self, eligible: list, n_r: int) -> None:
        """Structural change (membership or first tick): rebuild the row
        map and every array.  Counted — steady state must never get here."""
        self.full_rebuilds += 1
        n_w = len(eligible)
        self.worker_ids = [w.worker_id for w in eligible]
        self._workers = eligible
        self._epochs = [w.epoch for w in eligible]
        self.n_r = n_r
        self.free = np.zeros((n_w, n_r), dtype=np.int64)
        self.total = np.zeros((n_w, n_r), dtype=np.int64)
        self.nt_free = np.zeros(n_w, dtype=np.int32)
        self.lifetime = np.zeros(n_w, dtype=np.int32)
        self._timed_rows = []
        for i, w in enumerate(eligible):
            self._write_row(i, w)
            self.lifetime[i] = w.lifetime_secs()
            if w.configuration.time_limit_secs > 0:
                self._timed_rows.append(i)

    def _widen(self, n_r: int) -> None:
        """Resource map grew: pad new zero columns (a worker's dense row
        may lag the map right after a new name is interned — the scratch
        path zero-fills the same columns)."""
        grow = n_r - self.n_r
        self.free = np.pad(self.free, ((0, 0), (0, grow)))
        self.total = np.pad(self.total, ((0, 0), (0, grow)))
        self.n_r = n_r

    def _write_row(self, i: int, w) -> None:
        """Full row write: free, POOL TOTALS and nt_free.  Only rebuild
        and widening call this — pool totals are static per worker, so the
        per-tick dirty path (_refresh_free_row) skips them."""
        self._write_free_row(i, w)
        amounts = w.resources.amounts
        n = min(len(amounts), self.n_r)
        row = self.total[i]
        row[:n] = amounts[:n]
        row[n:] = 0

    def _write_free_row(self, i: int, w) -> None:
        free = w.free
        n = min(len(free), self.n_r)
        row = self.free[i]
        row[:n] = free[:n]
        row[n:] = 0
        self.nt_free[i] = w.nt_free if w.nt_free > 0 else 0

    # above this dirty fraction, one C-level bulk conversion of every row
    # beats per-row Python writes (a heavily-loaded tick can touch every
    # worker between schedules — incremental must not lose to scratch then)
    _BULK_DIRTY_FRACTION = 8

    def _refresh_dirty(self) -> None:
        self.incremental_syncs += 1
        epochs = self._epochs
        workers = self._workers
        dirty = [
            i for i, w in enumerate(workers) if w.epoch != epochs[i]
        ]
        n_w = len(workers)
        if dirty and len(dirty) > n_w // self._BULK_DIRTY_FRACTION:
            free_lists = [w.free for w in workers]
            n_r = self.n_r
            if all(len(f) == n_r for f in free_lists):
                # one-shot C conversion of every row into persistent
                # storage (fromiter over a chained iterator beats both
                # np.array(list-of-lists) and slice assignment ~2.4x);
                # pool totals are static and stay untouched
                from itertools import chain

                self.free[:] = np.fromiter(
                    chain.from_iterable(free_lists), dtype=np.int64,
                    count=n_w * n_r,
                ).reshape(n_w, n_r)
                np.maximum(
                    np.fromiter(
                        (w.nt_free for w in workers), dtype=np.int32,
                        count=n_w,
                    ),
                    0,
                    out=self.nt_free,
                )
                for i in dirty:
                    epochs[i] = workers[i].epoch
            else:
                for i in dirty:
                    self._write_free_row(i, workers[i])
                    epochs[i] = workers[i].epoch
        else:
            for i in dirty:
                self._write_free_row(i, workers[i])
                epochs[i] = workers[i].epoch
        for i in self._timed_rows:
            self.lifetime[i] = workers[i].lifetime_secs()
        self.rows_rewritten_last = len(dirty)

    # ------------------------------------------------------------------
    def counters(self) -> dict:
        return {
            "full_rebuilds": self.full_rebuilds,
            "incremental_syncs": self.incremental_syncs,
            "rows_rewritten_last": self.rows_rewritten_last,
            "workers": len(self.worker_ids),
            "resources": self.n_r,
        }


def paranoid_check(core, snapshot: DenseSnapshot, batches, rq_map,
                   resource_map, gang_ok=None, group_ids=None,
                   policy=None) -> None:
    """Assert the incremental assembly is bit-identical to from-scratch.

    Runs BOTH assemble paths on copies of the batch list (assemble sorts
    in place but pops nothing), and compares every kwargs array exactly —
    including the fused-gang inputs (gang_nodes/gang_ok/group_onehot)
    and the policy affinity matrix when the tick carries them.  Raises
    AssertionError naming the first differing array.  Debug tool:
    `hq server start --paranoid-tick N` runs this every N ticks.
    """
    from hyperqueue_tpu.scheduler.tick import Batch, assemble_solve_inputs

    def copy_batches(src):
        return [Batch(rq_id=b.rq_id, priority=b.priority, size=b.size,
                      gang_task=b.gang_task, gang_nodes=b.gang_nodes)
                for b in src]

    scratch_rows = [r for r in core.worker_rows() if r.cpu_floor <= 0]
    k_scratch = assemble_solve_inputs(
        scratch_rows, copy_batches(batches), rq_map, resource_map,
        gang_ok=gang_ok, group_ids=group_ids, policy=policy,
    )
    # key_cache=core.tick_cache: the check must exercise the SAME memoized
    # sort-key/batch-layout/needs32 path the production assemble uses, or
    # a corrupted memo would pass paranoid while feeding every real solve
    k_incr = assemble_solve_inputs(
        None, copy_batches(batches), rq_map, resource_map, dense=snapshot,
        key_cache=core.tick_cache, gang_ok=gang_ok, group_ids=group_ids,
        policy=policy,
    )
    scratch_ids = [r.worker_id for r in scratch_rows]
    assert scratch_ids == snapshot.worker_ids, (
        f"paranoid-tick: worker row order diverged "
        f"(scratch={scratch_ids[:8]}..., cache={snapshot.worker_ids[:8]}...)"
    )
    keys = set(k_scratch) | set(k_incr)
    for key in sorted(keys):
        a, b = k_scratch.get(key), k_incr.get(key)
        if key == "priorities":
            assert a == b, f"paranoid-tick: priorities diverged"
            continue
        assert (a is None) == (b is None), (
            f"paranoid-tick: key {key!r} present on one path only"
        )
        if a is None:
            continue
        a, b = np.asarray(a), np.asarray(b)
        if key == "lifetime" and a.shape == b.shape:
            # lifetime is wall-clock-derived for time-limited workers: the
            # cache stamped it at sync() and the scratch rows re-evaluate
            # it here, so crossing a 1-second boundary in between yields a
            # legitimate off-by-one — everything else must be exact
            assert np.abs(a.astype(np.int64) - b.astype(np.int64)).max(
                initial=0
            ) <= 1, (
                "paranoid-tick: lifetime diverged beyond clock granularity"
            )
            continue
        assert np.array_equal(a, b), (
            f"paranoid-tick: array {key!r} diverged between incremental "
            f"and from-scratch assembly"
        )
