"""Pure-Python reference implementation of the tick assignment semantics.

An independent, loop-based implementation of exactly the semantics the JAX
kernel (ops/assign.py) must satisfy. It is the executable spec for golden
tests (mirroring how the reference's tier-1 Rust tests encode scheduler
semantics, SURVEY.md §4) and is deliberately written in the dumbest possible
style — no vectorization — so a human can audit it against the reference's
solver behavior.
"""

from __future__ import annotations


def solve_oracle(free, nt_free, lifetime, needs, sizes, min_time, scarcity,
                 gang_nodes=None, gang_ok=None, group_ids=None,
                 affinity=None):
    """Same contract as ops.assign.greedy_cut_scan, lists/nested lists in,
    counts[b][v][w] out. Mutates nothing.

    Gang rows (all-or-nothing column groups, ops/assign.py scan_batches):
    gang_nodes[b] > 0 marks batch b as one multi-node gang; gang_ok[w] is
    host idleness and group_ids[w] the worker's group index. A gang row
    takes the first group with >= n still-untouched eligible members (the
    n lowest-index ones) and emits n counts in variant 0; feasible or not,
    the selected members are held (free/nt zeroed) for the rest of the
    scan, and any single-node assignment makes a worker ineligible for
    later gangs.

    affinity[b][w] (optional) is the policy weight matrix row per batch
    (scheduler/policy.py): workers are visited in (-affinity, waste, index)
    order — the same lexicographic key host_visit_classes encodes into
    visit classes — and a zero weight is a hard exclusion (the worker
    contributes no capacity and no gang membership for that batch).
    """
    n_w = len(free)
    n_r = len(free[0]) if n_w else 0
    free0 = [list(row) for row in free]  # visit order derives from this
    free = [list(row) for row in free]
    nt_free = list(nt_free)
    n_b = len(needs)
    n_v = len(needs[0]) if n_b else 0
    counts = [[[0] * n_w for _ in range(n_v)] for _ in range(n_b)]
    gang_avail = list(gang_ok) if gang_ok is not None else [0] * n_w
    n_g = (max(group_ids) + 1) if group_ids else 1

    for b in range(n_b):
        remaining = sizes[b]
        if gang_nodes is not None and gang_nodes[b] > 0:
            n = gang_nodes[b]
            per_group = [0] * n_g
            members: list[list[int]] = [[] for _ in range(n_g)]
            for w in range(n_w):
                if (
                    gang_avail[w]
                    and min_time[b][0] <= lifetime[w]
                    and nt_free[w] >= 1
                    and (affinity is None or affinity[b][w] > 0)
                ):
                    per_group[group_ids[w]] += 1
                    members[group_ids[w]].append(w)
            feasible = [g for g in range(n_g) if per_group[g] >= n]
            if feasible:
                chosen = feasible[0]
            else:
                chosen = per_group.index(max(per_group))
            for w in members[chosen][:n]:
                if feasible:
                    counts[b][0][w] = 1
                free[w] = [0] * n_r
                nt_free[w] = 0
                gang_avail[w] = 0
            continue
        for v in range(n_v):
            need = needs[b][v]
            if not any(x > 0 for x in need):
                continue  # absent variant
            # capacity per worker
            caps = []
            for w in range(n_w):
                if min_time[b][v] > lifetime[w]:
                    caps.append(0)
                    continue
                cap = nt_free[w]
                for r in range(n_r):
                    if need[r] > 0:
                        cap = min(cap, free[w][r] // need[r])
                caps.append(max(cap, 0))
            # worker order: policy affinity descending first (when active),
            # then scarcity-weighted waste of unrequested resources
            # (computed from the tick's INITIAL free state, like the kernel's
            # precomputed visit orders), then index
            def key(w):
                waste = sum(
                    scarcity[r]
                    for r in range(n_r)
                    if free0[w][r] > 0 and need[r] == 0
                )
                aff_q = (
                    0 if affinity is None
                    else round(affinity[b][w] * 65536)
                )
                return (-aff_q, round(waste * 65536), w)

            for w in sorted(range(n_w), key=key):
                if remaining <= 0:
                    break
                if affinity is not None and affinity[b][w] <= 0:
                    continue  # zero weight = hard exclusion
                take = min(caps[w], remaining)
                if take <= 0:
                    continue
                counts[b][v][w] = take
                remaining -= take
                nt_free[w] -= take
                gang_avail[w] = 0
                for r in range(n_r):
                    free[w][r] -= take * need[r]
    return counts


def explain_unplaced(
    free, nt_free, lifetime, needs, sizes, min_time, counts, total=None,
    gang_nodes=None, gang_ok=None, group_ids=None,
):
    """Reference classifier for WHY each batch's remainder stayed unplaced.

    The executable spec for scheduler/decision.classify_class, in the same
    deliberately dumb loop style as solve_oracle: given the tick's dense
    inputs and the solver's counts, return one reason string per batch
    (None for fully placed batches). `total` is the worker TOTAL capacity
    matrix (defaults to the tick-start `free`, which equals totals on an
    empty cluster snapshot). Gang rows (gang_nodes[b] > 0) classify as
    gang-incomplete when NO group could ever muster n lifetime-capable
    members, else gang-group-deferred (members exist but were busy or held
    this tick). Mutates nothing.
    """
    from hyperqueue_tpu.scheduler.decision import (
        REASON_GANG_GROUP_DEFERRED,
        REASON_GANG_INCOMPLETE,
        REASON_INSUFFICIENT_CAPACITY,
        REASON_NO_MATCHING_WORKER,
        REASON_SOLVER_DEFERRED,
        REASON_WORKER_LIFETIME,
    )

    n_w = len(free)
    n_r = len(free[0]) if n_w else 0
    n_b = len(needs)
    n_v = len(needs[0]) if n_b else 0
    if total is None:
        total = free
    # replay the assignments onto a scratch copy: the post-solve free state
    # decides insufficient-capacity vs solver-deferred
    post_free = [list(row) for row in free]
    post_nt = list(nt_free)
    for b in range(n_b):
        for v in range(n_v):
            for w in range(n_w):
                take = counts[b][v][w]
                if take > 0:
                    post_nt[w] -= take
                    for r in range(n_r):
                        post_free[w][r] -= take * needs[b][v][r]

    reasons = []
    n_g = (max(group_ids) + 1) if group_ids else 1
    for b in range(n_b):
        placed = sum(
            counts[b][v][w] for v in range(n_v) for w in range(n_w)
        )
        if gang_nodes is not None and gang_nodes[b] > 0:
            # all-or-nothing: the kernel emits either n counts or none
            if placed > 0:
                reasons.append(None)
                continue
            n = gang_nodes[b]
            per_group = [0] * n_g
            for w in range(n_w):
                if min_time[b][0] <= lifetime[w]:
                    per_group[group_ids[w]] += 1
            reasons.append(
                REASON_GANG_GROUP_DEFERRED
                if max(per_group, default=0) >= n
                else REASON_GANG_INCOMPLETE
            )
            continue
        if placed >= sizes[b]:
            reasons.append(None)
            continue
        present = [
            v for v in range(n_v) if any(x > 0 for x in needs[b][v])
        ]
        amount_capable = False
        lifetime_capable = False
        fits_now = False
        for w in range(n_w):
            for v in present:
                if all(
                    total[w][r] >= needs[b][v][r] for r in range(n_r)
                ):
                    amount_capable = True
                    if min_time[b][v] <= lifetime[w]:
                        lifetime_capable = True
                        if post_nt[w] >= 1 and all(
                            post_free[w][r] >= needs[b][v][r]
                            for r in range(n_r)
                        ):
                            fits_now = True
        if not amount_capable:
            reasons.append(REASON_NO_MATCHING_WORKER)
        elif not lifetime_capable:
            reasons.append(REASON_WORKER_LIFETIME)
        elif fits_now:
            reasons.append(REASON_SOLVER_DEFERRED)
        else:
            reasons.append(REASON_INSUFFICIENT_CAPACITY)
    return reasons
