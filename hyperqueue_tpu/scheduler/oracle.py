"""Pure-Python reference implementation of the tick assignment semantics.

An independent, loop-based implementation of exactly the semantics the JAX
kernel (ops/assign.py) must satisfy. It is the executable spec for golden
tests (mirroring how the reference's tier-1 Rust tests encode scheduler
semantics, SURVEY.md §4) and is deliberately written in the dumbest possible
style — no vectorization — so a human can audit it against the reference's
solver behavior.
"""

from __future__ import annotations


def solve_oracle(free, nt_free, lifetime, needs, sizes, min_time, scarcity):
    """Same contract as ops.assign.greedy_cut_scan, lists/nested lists in,
    counts[b][v][w] out. Mutates nothing."""
    n_w = len(free)
    n_r = len(free[0]) if n_w else 0
    free0 = [list(row) for row in free]  # visit order derives from this
    free = [list(row) for row in free]
    nt_free = list(nt_free)
    n_b = len(needs)
    n_v = len(needs[0]) if n_b else 0
    counts = [[[0] * n_w for _ in range(n_v)] for _ in range(n_b)]

    for b in range(n_b):
        remaining = sizes[b]
        for v in range(n_v):
            need = needs[b][v]
            if not any(x > 0 for x in need):
                continue  # absent variant
            # capacity per worker
            caps = []
            for w in range(n_w):
                if min_time[b][v] > lifetime[w]:
                    caps.append(0)
                    continue
                cap = nt_free[w]
                for r in range(n_r):
                    if need[r] > 0:
                        cap = min(cap, free[w][r] // need[r])
                caps.append(max(cap, 0))
            # worker order: scarcity-weighted waste of unrequested resources
            # (computed from the tick's INITIAL free state, like the kernel's
            # precomputed visit orders), then index
            def key(w):
                waste = sum(
                    scarcity[r]
                    for r in range(n_r)
                    if free0[w][r] > 0 and need[r] == 0
                )
                return (round(waste * 65536), w)

            for w in sorted(range(n_w), key=key):
                if remaining <= 0:
                    break
                take = min(caps[w], remaining)
                if take <= 0:
                    continue
                counts[b][v][w] = take
                remaining -= take
                nt_free[w] -= take
                for r in range(n_r):
                    free[w][r] -= take * need[r]
    return counts
