"""Scheduling policy: heterogeneity weights, fairness, runtime prediction.

The fused solve (ops/assign.py via models/greedy.py) maximizes raw placement
count; this module is the objective on top of that mechanism — the "policy
brain" of `--scheduler greedy-fused`:

* **Heterogeneity weights** (Gavel, arxiv 2008.09213): a per-(task-class,
  worker-class) throughput/affinity matrix `S`. Task class = the "+"-joined
  sorted resource names of the request's first variant ("nodes" for
  multi-node gangs — the same label `ResourceRequest.short_desc` renders);
  worker class = the worker's group. The matrix folds into the kernel's
  visit-class ordering (host_visit_classes lexsorts (-affinity, waste)), so
  high-throughput workers are water-filled first, and a zero weight is a
  hard exclusion (the batched policy mask zeroes the worker's capacity).

* **Fairness**: per-job dominant-resource deficit from the accounting
  ledger (server/accounting.py), folded into the priority encoding as a
  bounded boost (weighted max-min): a job whose dominant share sits under
  the 1/n fair share jumps ahead of up to `max_boost` earlier-submitted
  jobs (scheduler/queues.py BLEVEL_STRIDE arithmetic). The per-tick Jain
  index of instantaneous running usage is exported as a gauge.

* **Runtime prediction** (scheduler/predict.py): per-task-class runtime
  EWMAs weight the priority encoding with expected remaining work (LPT):
  classes predicted longest get the largest bounded boost, so straggler
  tails and deep DAGs start their critical path first.

Operator surface: `--policy-file <toml>`:

    [affinity."cpus"]        # task class (see above)
    "*"    = 1.0             # default worker-class weight
    fast   = 2.0             # worker group "fast"
    slow   = 0.0             # 0 = hard exclusion

    [fairness]
    enabled   = true
    max_boost = 4            # priority-encoding jump bound

    [prediction]
    enabled      = true
    max_boost    = 4
    ewma_alpha   = 0.3
    seed_journal = "/path/to/journal"   # optional offline seed (PR 14)

Everything here is host-side numpy/dict work computed once per tick; the
only thing that crosses into the kernel is the (B, W) affinity matrix and
its derived mask. Degraded modes inherit the weights wholesale: the numpy
twin, the watchdog's host fallback, `--tick-pipeline` and `--paranoid-tick`
all consume the same per-solve inputs, so no path schedules unweighted.
"""

from __future__ import annotations

import numpy as np

from hyperqueue_tpu.scheduler.queues import decode_sched_job

DEFAULT_MAX_BOOST = 4
DEFAULT_EWMA_ALPHA = 0.3


def task_class(variants, resource_map) -> str:
    """Stable class label for a request: "nodes" for multi-node gangs, else
    the sorted "+"-joined resource names of the FIRST variant (the user's
    preferred shape — variants of one request share a class)."""
    v0 = variants.variants[0]
    if v0.n_nodes > 0:
        return "nodes"
    names = resource_map.names()
    parts = sorted(
        names[e.resource_id] if e.resource_id < len(names)
        else f"res{e.resource_id}"
        for e in v0.entries
    )
    return "+".join(parts) if parts else "none"


class PolicyTable:
    """Parsed, validated policy config (TOML file or built-in flat)."""

    def __init__(
        self,
        affinity: dict | None = None,
        fairness_enabled: bool = False,
        fairness_max_boost: int = DEFAULT_MAX_BOOST,
        prediction_enabled: bool = False,
        prediction_max_boost: int = DEFAULT_MAX_BOOST,
        ewma_alpha: float = DEFAULT_EWMA_ALPHA,
        seed_journal: str | None = None,
        source: str = "builtin",
    ):
        # {task_class: {worker_class_or_*: weight}}
        self.affinity = affinity or {}
        self.fairness_enabled = bool(fairness_enabled)
        self.fairness_max_boost = max(int(fairness_max_boost), 0)
        self.prediction_enabled = bool(prediction_enabled)
        self.prediction_max_boost = max(int(prediction_max_boost), 0)
        self.ewma_alpha = float(ewma_alpha)
        self.seed_journal = seed_journal
        self.source = source

    @classmethod
    def from_file(cls, path: str) -> "PolicyTable":
        try:
            import tomllib
        except ModuleNotFoundError:  # Python < 3.11
            import tomli as tomllib

        with open(path, "rb") as f:
            data = tomllib.load(f)
        affinity = {}
        for tclass, row in (data.get("affinity") or {}).items():
            if not isinstance(row, dict):
                raise ValueError(
                    f"policy file {path}: [affinity.\"{tclass}\"] must be a "
                    "table of worker-class = weight entries"
                )
            parsed = {}
            for wclass, weight in row.items():
                w = float(weight)
                if w < 0:
                    raise ValueError(
                        f"policy file {path}: affinity weight for "
                        f"({tclass!r}, {wclass!r}) is negative"
                    )
                parsed[wclass] = w
            affinity[tclass] = parsed
        fair = data.get("fairness") or {}
        pred = data.get("prediction") or {}
        return cls(
            affinity=affinity,
            fairness_enabled=fair.get("enabled", False),
            fairness_max_boost=fair.get("max_boost", DEFAULT_MAX_BOOST),
            prediction_enabled=pred.get("enabled", False),
            prediction_max_boost=pred.get("max_boost", DEFAULT_MAX_BOOST),
            ewma_alpha=pred.get("ewma_alpha", DEFAULT_EWMA_ALPHA),
            seed_journal=pred.get("seed_journal"),
            source=str(path),
        )

    def has_row(self, tclass: str) -> bool:
        return tclass in self.affinity or "*" in self.affinity

    def weight(self, tclass: str, wclass: str) -> float:
        row = self.affinity.get(tclass)
        if row is None:
            row = self.affinity.get("*")
        if row is None:
            return 1.0
        w = row.get(wclass)
        if w is None:
            w = row.get("*", 1.0)
        return float(w)


class TickPolicyContext:
    """One tick's resolved policy inputs, aligned to the solve's worker
    order: per-rq affinity rows for assemble_solve_inputs, per-job priority
    boosts for the batch sort. Built once per tick by PolicyState."""

    __slots__ = ("rows", "boosts")

    def __init__(self, rows: dict, boosts: dict):
        self.rows = rows      # rq_id -> (W,) float32 row (aligned)
        self.boosts = boosts  # job_id -> int boost (>= 1 entries only)

    def affinity_for(self, rq_id: int):
        return self.rows.get(rq_id)

    def boost_for(self, job_id: int) -> int:
        return self.boosts.get(job_id, 0)

    def boost_for_sched(self, sched: int) -> int:
        return self.boosts.get(decode_sched_job(sched), 0)

    def __bool__(self) -> bool:
        return bool(self.rows) or bool(self.boosts)


class PolicyState:
    """Live policy engine: owns the table, the runtime predictor, and the
    fairness fold over the accounting ledger; produces one
    TickPolicyContext per scheduling tick and the telemetry the stats/
    explain surfaces render."""

    def __init__(self, table: PolicyTable, predictor=None, ledger=None,
                 job_name=None, live_jobs=None):
        self.table = table
        self.predictor = predictor
        self.ledger = ledger
        # job_id -> display name; the predictor's class key. Falls back to
        # the ledger row label when no resolver is injected.
        self._job_name = job_name
        # () -> iterable of job ids with unfinished tasks; lets the Jain
        # fold count STARVED jobs (work pending, zero usage) at 0 — without
        # it a schedule that serializes jobs one at a time scores a perfect
        # 1.0, the opposite of what the fairness gauge should say.
        self._live_jobs = live_jobs
        self.last_boost_range = (0, 0)
        self.last_jain: float | None = None
        self._jain_sum = 0.0
        self._jain_ticks = 0
        self._class_cache: dict[int, str] = {}

    # -- per-tick context -------------------------------------------------
    def tick_context(self, workers_by_id, rq_map, resource_map, worker_ids,
                     batches):
        """Resolve this tick's affinity rows + priority boosts.

        workers_by_id: core.workers; worker_ids: the solve's worker order
        (the affinity rows index-align with it); batches: the tick's batch
        list (the active job/rq universe). Returns a TickPolicyContext, or
        None when the policy has no effect this tick (the flat fast path).
        """
        rows: dict[int, np.ndarray] = {}
        if self.table.affinity and worker_ids and batches:
            wclasses = []
            for wid in worker_ids:
                w = workers_by_id.get(wid)
                wclasses.append(getattr(w, "group", "") or "default")
            row_cache: dict[str, np.ndarray | None] = {}
            for b in batches:
                if b.rq_id in rows:
                    continue
                tclass = self._task_class_of(b.rq_id, rq_map, resource_map)
                if tclass is None or not self.table.has_row(tclass):
                    continue
                row = row_cache.get(tclass)
                if row is None and tclass not in row_cache:
                    vals = np.fromiter(
                        (self.table.weight(tclass, wc) for wc in wclasses),
                        dtype=np.float32, count=len(wclasses),
                    )
                    # a uniform positive row cannot reorder or exclude
                    row = (
                        vals
                        if (vals.min() != vals.max() or vals.min() <= 0)
                        else None
                    )
                    row_cache[tclass] = row
                if row is not None:
                    rows[b.rq_id] = row
        boosts = self._job_boosts(batches)
        if not rows and not boosts:
            return None
        return TickPolicyContext(rows, boosts)

    def _task_class_of(self, rq_id, rq_map, resource_map):
        cached = self._class_cache.get(rq_id)
        if cached is not None:
            return cached
        try:
            tclass = task_class(rq_map.get_variants(rq_id), resource_map)
        except (KeyError, IndexError):
            return None
        self._class_cache[rq_id] = tclass
        return tclass

    def _resolve_name(self, job_id: int) -> str | None:
        if self._job_name is not None:
            try:
                name = self._job_name(job_id)
            except Exception:  # noqa: BLE001 - telemetry, not control flow
                name = None
            if name:
                return name
        if self.ledger is not None:
            row = self.ledger.rows.get(job_id)
            if row:
                return row.get("label")
        return None

    def _job_boosts(self, batches) -> dict[int, int]:
        """Bounded per-job priority boosts: fairness deficit + predicted
        LPT, each capped by its own max_boost. Deterministic: pure folds
        over the ledger and predictor tables in sorted job order."""
        active = sorted({
            decode_sched_job(b.priority[1]) for b in (batches or [])
        })
        boosts: dict[int, int] = {}
        if not active:
            self.last_boost_range = (0, 0)
            return boosts
        if (
            self.table.fairness_enabled
            and self.ledger is not None
            and len(active) > 1
            and self.table.fairness_max_boost > 0
        ):
            usage = {}
            totals: dict[str, float] = {}
            for j in active:
                row = self.ledger.rows.get(j)
                rs = (row.get("resource_seconds") or {}) if row else {}
                usage[j] = rs
                for r, amt in rs.items():
                    totals[r] = totals.get(r, 0.0) + amt
            fair = 1.0 / len(active)
            for j in active:
                share = 0.0
                for r, amt in usage[j].items():
                    tot = totals.get(r, 0.0)
                    if tot > 0:
                        share = max(share, amt / tot)
                if share < fair:
                    boost = int(round(
                        self.table.fairness_max_boost * (1.0 - share / fair)
                    ))
                    if boost > 0:
                        boosts[j] = boost
        if (
            self.table.prediction_enabled
            and self.predictor is not None
            and self.table.prediction_max_boost > 0
        ):
            preds = {}
            for j in active:
                name = self._resolve_name(j)
                if name is None:
                    continue
                p = self.predictor.predict(name)
                if p is not None and p > 0:
                    preds[j] = p
            if preds:
                pmax = max(preds.values())
                if pmax > 0:
                    for j, p in preds.items():
                        boost = int(round(
                            self.table.prediction_max_boost * (p / pmax)
                        ))
                        if boost > 0:
                            boosts[j] = boosts.get(j, 0) + boost
        if boosts:
            vals = boosts.values()
            self.last_boost_range = (min(vals), max(vals))
        else:
            self.last_boost_range = (0, 0)
        return boosts

    # -- fairness telemetry ----------------------------------------------
    def observe_jain(self) -> float | None:
        """Jain fairness index of the instantaneous running usage per job,
        folded from the ledger's open runs (journal-deterministic). Jobs
        that still have unfinished tasks but hold NOTHING right now count
        at zero usage — starving a tenant must lower the index, not drop
        the tenant from it. None when nothing is running; folded into the
        time-averaged stat only when at least one job holds resources."""
        if self.ledger is None:
            return None
        per_job: dict[int, float] = {}
        for (job, _task), run in self.ledger.open_runs.items():
            amount = sum((run.get("usage") or {}).values())
            per_job[job] = per_job.get(job, 0.0) + amount
        if not any(v > 0 for v in per_job.values()):
            return None
        if self._live_jobs is not None:
            try:
                for j in self._live_jobs():
                    per_job.setdefault(j, 0.0)
            except Exception:  # noqa: BLE001 - telemetry, not control flow
                pass
        xs = [v for v in per_job.values() if v >= 0]
        s = sum(xs)
        s2 = sum(x * x for x in xs)
        jain = (s * s) / (len(xs) * s2) if s2 > 0 else 1.0
        self.last_jain = jain
        self._jain_sum += jain
        self._jain_ticks += 1
        return jain

    # -- surfaces ---------------------------------------------------------
    def stats(self) -> dict:
        out = {
            "source": self.table.source,
            "affinity_classes": len(self.table.affinity),
            "fairness": {
                "enabled": self.table.fairness_enabled,
                "max_boost": self.table.fairness_max_boost,
            },
            "prediction": {
                "enabled": self.table.prediction_enabled,
                "max_boost": self.table.prediction_max_boost,
            },
            "boost_range": list(self.last_boost_range),
        }
        if self.predictor is not None:
            out["prediction"].update(self.predictor.stats())
        if self._jain_ticks:
            out["jain"] = {
                "last": round(self.last_jain, 4),
                "avg": round(self._jain_sum / self._jain_ticks, 4),
                "ticks": self._jain_ticks,
            }
        return out


def build_policy(policy_file: str | None, ledger=None, job_name=None,
                 live_jobs=None):
    """Bootstrap entry: parse `--policy-file`, build the predictor (seeding
    it offline when the table names a journal), and return a PolicyState —
    or None when no policy file is configured (the flat objective)."""
    if not policy_file:
        return None
    from hyperqueue_tpu.scheduler.predict import RuntimePredictor

    table = PolicyTable.from_file(policy_file)
    predictor = None
    if table.prediction_enabled:
        predictor = RuntimePredictor(alpha=table.ewma_alpha)
        if table.seed_journal:
            import os

            if os.path.exists(table.seed_journal):
                predictor.seed_from_journal(table.seed_journal)
    return PolicyState(
        table, predictor=predictor, ledger=ledger, job_name=job_name,
        live_jobs=live_jobs,
    )
