"""One scheduling tick: queues -> batches -> dense snapshot -> solve -> mapping.

Reference factoring: crates/tako/src/internal/scheduler/main.rs:40-46
(batches -> solver -> mapping). The dense snapshot is the seam where the work
moves to the TPU: everything up to `model.solve` is host bookkeeping over
dicts; the solve itself sees only integer tensors (SURVEY.md §3.2).

Batches: per rq-id queue, each distinct priority level becomes a cut, capped
at MAX_CUTS_PER_QUEUE with the tail merged into the last cut (reference
batches.rs:183-217 prunes similarly). Batches from all queues are solved
jointly, globally ordered by priority.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass

import numpy as np

from hyperqueue_tpu.utils.constants import INF_TIME
from hyperqueue_tpu.resources.map import ResourceIdMap, ResourceRqMap
from hyperqueue_tpu.scheduler.queues import Priority, TaskQueues

MAX_CUTS_PER_QUEUE = 32
# Node budget for the per-worker min-utilization branch-and-bound
# (_solve_mu_workers): past this the best fill FOUND still ships (the
# first dive is a greedy max-take seed) and a warning names the worker.
MU_DFS_NODE_BUDGET = 50_000
# Values above this get range-compressed before entering the kernel — the
# kernel requires amounts to be float32-exact (ops/assign.MAX_KERNEL_AMOUNT).
MAX_SAFE_AMOUNT = 2**23


@dataclass(slots=True)
class Batch:
    rq_id: int
    priority: Priority
    size: int
    # fused gang rows (reactor fused mode): gang_task is the multi-node
    # task this row represents, gang_nodes its node count.  The solve
    # co-schedules the gang atomically (ops/assign.py gang rows); the
    # mapping emits (gang_task, worker, rq, -1) sentinels — gang tasks
    # live in core.mn_queue, never in the per-rq TaskQueues.
    gang_task: int = 0
    gang_nodes: int = 0


@dataclass(slots=True)
class WorkerRow:
    worker_id: int
    free: list[int]       # dense fractions, aligned to ResourceIdMap
    nt_free: int
    lifetime_secs: int    # INF_TIME if unlimited
    # pool totals (None = use free; only read for ALL-policy requests)
    total: list[int] | None = None
    # min-utilization floor in cpu fractions still to fill before this worker
    # may take any task at all (reference worker configuration
    # min_utilization, solver.rs:479-518); 0 = normal worker
    cpu_floor: int = 0


# One assignment is a plain (task_id, worker_id, rq_id, variant) tuple:
# at 16k+ assignments per tick, object construction dominated the mapping
# phase (dataclass/NamedTuple are ~5x slower to build than tuples).
Assignment = tuple[int, int, int, int]


def create_batches(queues: TaskQueues) -> list[Batch]:
    batches: list[Batch] = []
    for rq_id, queue in queues.items():
        sizes = queue.priority_sizes()
        if len(sizes) > MAX_CUTS_PER_QUEUE:
            head = sizes[: MAX_CUTS_PER_QUEUE - 1]
            tail_count = sum(n for _, n in sizes[MAX_CUTS_PER_QUEUE - 1 :])
            tail_priority = sizes[MAX_CUTS_PER_QUEUE - 1][0]
            sizes = head + [(tail_priority, tail_count)]
        for priority, count in sizes:
            batches.append(Batch(rq_id=rq_id, priority=priority, size=count))
    batches.sort(key=lambda b: (b.priority, -b.rq_id), reverse=True)
    return batches


def _compress_shifts(
    needs: np.ndarray, free: np.ndarray, total: np.ndarray | None = None
) -> list[int]:
    """Per-column shifts needed to keep every amount float32-exact.

    Pure: reads peaks only, mutates nothing.  All-zero in the common case
    (amounts below MAX_SAFE_AMOUNT), which lets the incremental assemble
    hand the model the cache-owned arrays without copying them.
    """
    shifts = [0] * free.shape[1]
    for r in range(free.shape[1]):
        peak = max(
            int(free[:, r].max(initial=0)), int(needs[:, :, r].max(initial=0))
        )
        if total is not None:
            peak = max(peak, int(total[:, r].max(initial=0)))
        shift = 0
        while (peak >> shift) >= MAX_SAFE_AMOUNT:
            shift += 1
        shifts[r] = shift
    return shifts


def _apply_compression(
    shifts: list[int],
    needs: np.ndarray,
    free: np.ndarray,
    total: np.ndarray | None = None,
) -> None:
    """Apply precomputed column shifts IN PLACE.

    needs are ceil-shifted (request never shrinks to zero) and free floor-
    shifted, so feasibility decisions stay sound (never optimistic). When
    `total` is present (ALL-policy requests in this tick) it shifts with
    free, and a partially-used pool is kept STRICTLY below its shifted total
    so the kernel's free == total idle check can never go optimistic.
    """
    for r, shift in enumerate(shifts):
        if not shift:
            continue
        nonzero = needs[:, :, r] > 0
        needs[:, :, r] = np.where(
            nonzero,
            np.maximum((needs[:, :, r] + (1 << shift) - 1) >> shift, 1),
            0,
        )
        was_partial = (
            free[:, r] < total[:, r] if total is not None else None
        )
        free[:, r] >>= shift
        if total is not None:
            total[:, r] >>= shift
            np.minimum(
                free[:, r],
                np.where(was_partial, total[:, r] - 1, free[:, r]),
                out=free[:, r],
            )


def _range_compress(
    needs: np.ndarray, free: np.ndarray, total: np.ndarray | None = None
) -> list[int]:
    """Shift-compress out-of-range columns in place; returns the shifts
    (callers scaling other cpu-denominated vectors, e.g. cpu_floor, must
    apply column 0's shift).  Composition of _compress_shifts +
    _apply_compression."""
    shifts = _compress_shifts(needs, free, total)
    _apply_compression(shifts, needs, free, total)
    return shifts


def run_tick(
    queues: TaskQueues,
    workers: list[WorkerRow] | None,
    rq_map: ResourceRqMap,
    resource_map: ResourceIdMap,
    model,
    batches: list[Batch] | None = None,
    dense=None,
    phases: dict | None = None,
    key_cache=None,
    decision: dict | None = None,
    pipeline=None,
    gang_ok=None,
    group_ids=None,
    policy=None,
) -> list[Assignment]:
    """Solve one tick and pop assigned tasks from the queues.

    Removes assigned tasks from `queues`; does NOT touch worker resource
    accounting — the caller (reactor) applies each Assignment to its Worker
    state, which keeps one owner for the free/nt_free bookkeeping.

    `batches` lets the caller pass a precomputed create_batches(queues)
    result (the reactor builds it once per schedule() and reuses it for the
    prefill phase); the caller's list order is left untouched.

    `dense` (a tick_cache.DenseSnapshot) replaces `workers` with the
    persistent incremental snapshot: the cache only serves ticks with no
    min-utilization workers, so the mu carve-out below is skipped
    structurally.  `phases` (optional dict) collects a per-phase latency
    breakdown in ms; `key_cache` memoizes sort keys across ticks;
    `decision` (optional dict) receives the solver's verdict for this
    tick's DecisionRecord (scheduler/decision.py): status, backend,
    solve_ms, objective.

    `pipeline` (a scheduler/pipeline.TickPipeline, dense path only)
    switches this tick to ASYNC dispatch: the solve is enqueued via
    `model.solve_async` and registered as the pipeline's pending solve,
    and THIS call returns no assignments — the caller maps the pending
    solve at the top of its next tick (pipeline.take_result), overlapping
    the device execution with the inter-tick host work.

    `policy` (a scheduler/policy.TickPolicyContext) carries this tick's
    resolved heterogeneity-affinity rows and per-job priority boosts; both
    fold into assemble_solve_inputs (the boost into the batch sort, the
    rows into the (B, W) affinity matrix the model consumes), so every
    solve path — device, numpy twin, watchdog fallback, pipelined — sees
    the same weighted objective.
    """
    if batches is None:
        batches = create_batches(queues)
    else:
        batches = list(batches)  # sorted in place below; don't reorder caller
    if dense is not None:
        if not batches or not dense.worker_ids:
            return []
        return _run_main_solve(
            queues, None, rq_map, resource_map, model, batches,
            dense=dense, phases=phases, key_cache=key_cache,
            decision=decision, pipeline=pipeline,
            gang_ok=gang_ok, group_ids=group_ids, policy=policy,
        )
    if not batches or not workers:
        return []

    # min-utilization workers take tasks all-or-nothing (enough to clear
    # their cpu floor, or none).  A model that can express that jointly
    # (MilpModel.supports_cpu_floor, `--scheduler=milp`) solves normal and
    # mu workers in one program, the reference semantics.  The dense
    # water-fill cannot, so under greedy/multichip the mu workers are
    # carved out of the main solve and each gets an exact host-side search
    # over the leftovers — a DOCUMENTED deviation (docs/scheduler.md
    # "Min-utilization workers"; pinned by tests/test_makespan.py
    # test_mu_carveout_vs_joint_oracle_disagree): a task never chooses
    # BETWEEN a normal and a mu worker in one decision.
    mu_workers = [w for w in workers if w.cpu_floor > 0]
    if mu_workers and getattr(model, "supports_cpu_floor", False):
        # joint path (reference solver.rs:479-518 add_min_utilization): the
        # model expresses the all-or-nothing floor itself, so normal and mu
        # workers are solved in ONE program — no carve-out deviation
        return _run_main_solve(
            queues, workers, rq_map, resource_map, model, batches,
            cpu_floor=np.fromiter(
                (max(w.cpu_floor, 0) for w in workers), dtype=np.int64,
                count=len(workers),
            ),
            phases=phases, key_cache=key_cache, decision=decision,
            policy=policy,
        )
    workers = [w for w in workers if w.cpu_floor <= 0]
    if not workers:
        return _solve_mu_workers(queues, mu_workers, rq_map, resource_map)
    if mu_workers and policy is not None and policy.rows:
        # the mu carve-out just dropped workers from the row list, so the
        # (B, W) affinity rows (built against the unfiltered order) no
        # longer align — keep only the alignment-free priority boosts
        policy = type(policy)(rows={}, boosts=policy.boosts)
    assignments = _run_main_solve(
        queues, workers, rq_map, resource_map, model, batches,
        phases=phases, key_cache=key_cache, decision=decision,
        policy=policy,
    )
    if mu_workers:
        assignments.extend(
            _solve_mu_workers(queues, mu_workers, rq_map, resource_map)
        )
    return assignments


def assemble_solve_inputs(workers, batches, rq_map, resource_map,
                          cpu_floor=None, dense=None, key_cache=None,
                          gang_ok=None, group_ids=None, policy=None):
    """Build the dense model.solve inputs for `batches` over `workers`.

    Sorts `batches` IN PLACE into the production solve order (priority,
    scarcity, achievable objective) and applies range compression so every
    amount is float32-exact for the jitted kernel.  This is the ONE
    assembly path, used by both the production tick (_run_main_solve) and
    the autoalloc demand query (autoalloc/query.py compute_new_worker_query)
    — sharing it guarantees the demand estimate can never diverge from
    what production would solve.  Returns the kwargs dict for
    model.solve().

    Two input forms, bit-identical by contract (tick_cache.paranoid_check):

    - `workers`: a list of WorkerRow — the from-scratch path, rebuilding
      the (W, R) arrays from Python lists each call;
    - `dense`: a tick_cache.DenseSnapshot — the incremental path; the
      persistent cache arrays are used directly (read-only: copied only
      when a range-compression shift must mutate them).

    `key_cache` (a TickStateCache, optional) memoizes the per-request-class
    (scarcity, objective) sort keys across ticks: they are pure in the rq
    class and this tick's free column totals, which steady-state ticks
    repeat.
    """
    n_r = len(resource_map)
    n_b = len(batches)
    n_v = max(
        len(rq_map.get_variants(b.rq_id).variants) for b in batches
    )

    from hyperqueue_tpu.resources.request import AllocationPolicy

    # ALL-policy requests need the pool totals alongside free (the kernel's
    # idle check); only materialized when some batch actually uses ALL
    has_all = any(
        entry.policy is AllocationPolicy.ALL
        for b in batches
        for variant in rq_map.get_variants(b.rq_id).variants
        for entry in variant.entries
    )

    if dense is not None:
        n_w = len(dense.worker_ids)
        free = dense.free
        total = dense.total if has_all else None
        nt_free = dense.nt_free
        lifetime = dense.lifetime
        cache_owns_arrays = True
    else:
        n_w = len(workers)
        free_lists = [row.free for row in workers]
        if all(len(f) == n_r for f in free_lists):
            # uniform rows (steady state): one C-level conversion instead
            # of a per-worker Python fill loop (~1.4 ms at 1k workers)
            free = np.array(free_lists, dtype=np.int64)
        else:
            # a worker's dense row can lag the global resource map right
            # after a new resource name is interned
            free = np.zeros((n_w, n_r), dtype=np.int64)
            for i, f in enumerate(free_lists):
                free[i, : len(f)] = f
        total = None
        if has_all:
            total = np.zeros((n_w, n_r), dtype=np.int64)
            for i, row in enumerate(workers):
                src = row.total if row.total is not None else row.free
                total[i, : min(len(src), n_r)] = src[:n_r]
        nt_free = np.fromiter(
            (row.nt_free if row.nt_free > 0 else 0 for row in workers),
            dtype=np.int32,
            count=n_w,
        )
        lifetime = np.fromiter(
            (row.lifetime_secs for row in workers), dtype=np.int32, count=n_w
        )
        cache_owns_arrays = False

    # Most-constrained-first within a priority level: a class that can ONLY
    # run on scarce resources is placed before same-priority classes with
    # more options, so flexible work cannot strand the few workers carrying
    # a scarce pool (the reference MILP reaches the same outcome by solving
    # the level jointly, solver.rs; pinned by
    # test_scheduler_golden.test_gap_filling2_exact_class_counts).
    # Constrainedness is the MINIMUM over variants: a class with a
    # commodity-resource fallback is flexible no matter how scarce its
    # preferred variant is, and ordering it first would let its fallback
    # spill eat the common pool ahead of cheaper classes.
    # One scarcity notion for the whole solve (ops/assign.scarcity_weights,
    # also used for worker visit order): zero-capacity resources weigh 0
    # (an unschedulable class must not sort first), and free is clamped at 0
    # — over-commit from prefill races can drive worker free negative, like
    # the nt_free clamp above.
    from hyperqueue_tpu.ops.assign import scarcity_weights

    col_totals = np.maximum(free, 0).sum(axis=0)
    weights = scarcity_weights(col_totals)

    def _scarcity(batch: Batch) -> float:
        score = float("inf")
        for variant in rq_map.get_variants(batch.rq_id).variants:
            v_score = 0.0
            for entry in variant.entries:
                if (
                    entry.amount > 0
                    or entry.policy is AllocationPolicy.ALL
                ) and entry.resource_id < n_r:
                    s = float(weights[entry.resource_id])
                    if s > v_score:
                        v_score = s
            if v_score < score:
                score = v_score
        return 0.0 if score == float("inf") else score

    # plain Python list: the sort key touches these per batch per entry and
    # numpy scalar indexing is ~10x a list index on this path
    totals_by_r = col_totals.tolist()
    # the (scarcity, objective) key is pure per request class + this tick's
    # totals; distinct classes per tick << batches (priority levels), so
    # memoize per rq_id for the sort below — and ACROSS ticks through
    # `key_cache` when the totals signature repeats (steady state:
    # releases and re-assignments cancel out tick-over-tick)
    sig = (n_w, n_r, tuple(totals_by_r))
    if key_cache is not None:
        if key_cache.sort_key_sig == sig:
            _key_cache = key_cache.sort_keys
        else:
            _key_cache = {}
            key_cache.sort_key_sig = sig
            key_cache.sort_keys = _key_cache
    else:
        _key_cache = {}

    def _objective_value(rq_id: int) -> list[tuple[float, float]]:
        """Within equal scarcity, emulate the reference LP objective
        (solver.rs:528-546): classes are taken in descending ACHIEVABLE
        share value — weight x per-task share-density x how many could run
        now (aggregate upper bound, O(R)) — with equal-value ties going to
        the smaller per-task ask (more tasks fit; the reference LP is
        indifferent and its worker-order bonus resolves the same way).
        Request weights (request.rs:137 ResourceWeight) scale the value, so
        `--weight` biases which equal-scarcity class wins. Pinned by golden
        multiple_resources2 / generic_resource_assign2 /
        generic_resource_balance2 / resource_weights1-2.

        Returns [(value, fit), ...] per variant; the sort maximizes
        (value x min(size, fit), -value) over them with the batch size."""
        out = []
        for variant in rq_map.get_variants(rq_id).variants:
            share = 0.0
            fit = float("inf")
            for entry in variant.entries:
                if entry.resource_id >= n_r:
                    fit = 0.0
                    break
                tot = totals_by_r[entry.resource_id]
                if entry.policy is AllocationPolicy.ALL:
                    # amount is the worker's whole pool; approximate the
                    # share with the per-worker average
                    share += 1.0 / max(n_w, 1)
                    fit = min(fit, float(n_w))
                elif entry.amount > 0:
                    if tot <= 0:
                        fit = 0.0
                        break
                    share += entry.amount / tot
                    fit = min(fit, tot // entry.amount)
            if fit == float("inf"):
                fit = 0.0
            out.append((variant.weight * share, fit))
        return out

    # policy priority boosts (scheduler/policy.py): a boosted job's batches
    # sort as if the job had been submitted `boost` jobs earlier — one
    # BLEVEL_STRIDE per boost step, the same arithmetic the sched encoding
    # uses for job ordering (queues.encode_sched_priority).  The batch's
    # own priority tuple is NOT mutated: the mapping phase and the decision
    # record keep the original submission order.
    pol_boosts = policy is not None and bool(policy.boosts)
    if pol_boosts:
        from hyperqueue_tpu.scheduler.queues import BLEVEL_STRIDE

    def _sort_key(b: Batch):
        cached = _key_cache.get(b.rq_id)
        if cached is None:
            cached = (_scarcity(b), _objective_value(b.rq_id))
            _key_cache[b.rq_id] = cached
        scarcity, per_variant = cached
        # the achievable objective depends on the batch SIZE, so the best
        # variant is chosen here, per batch, from the cached class values
        best = (0.0, 0.0)
        size = b.size
        for value, fit in per_variant:
            cand = (value * (size if size < fit else fit), -value)
            if cand > best:
                best = cand
        sched = b.priority[1]
        if pol_boosts:
            boost = policy.boost_for_sched(sched)
            if boost:
                sched = sched + boost * BLEVEL_STRIDE
        # gang rows sort ahead of same-user-priority single-node work (the
        # in-solve mirror of the host gang phase running before the dense
        # solve); without the boost a deep filler backlog would touch every
        # idle worker before any gang row scans, starving gangs forever
        return (
            (b.priority[0], 1 if b.gang_nodes else 0, sched),
            scarcity, best,
        )

    batches.sort(key=_sort_key, reverse=True)

    # per-tick sizes always refresh; the batch-shaped LAYOUT arrays
    # (needs/min_time/all_mask/weights) are pure in the sorted rq-id
    # sequence and reusable across ticks through `key_cache` — steady
    # state repeats the sequence exactly
    sizes = np.fromiter(
        (b.size if b.size < 2**30 else 2**30 for b in batches),
        dtype=np.int32, count=n_b,
    )
    layout = None
    layout_sig = None
    if key_cache is not None:
        layout_sig = (
            n_b, n_v, n_r, has_all, tuple(b.rq_id for b in batches)
        )
        if key_cache.batch_layout_sig == layout_sig:
            layout = key_cache.batch_layout
    if layout is not None:
        needs = layout["needs64"]
        min_time = layout["min_time"]
        all_mask = layout["all_mask"]
        w_arr = layout["w_arr"]
        needs_cache_owned = True
    else:
        needs = np.zeros((n_b, n_v, n_r), dtype=np.int64)
        min_time = np.zeros((n_b, n_v), dtype=np.int32)
        min_time[:] = int(INF_TIME)  # absent variants never eligible
        all_mask = (
            np.zeros((n_b, n_v, n_r), dtype=np.int32) if has_all else None
        )
        # dense rows per request class are immutable — cache them on the
        # rq_map (keyed by the resource-map width, which can grow) instead
        # of re-walking every entry of every batch each tick
        cache_key, dense_cache = getattr(rq_map, "_dense_cache", (None, None))
        if cache_key != n_r:
            dense_cache = {}
            rq_map._dense_cache = (n_r, dense_cache)
        weighted_rows: list[tuple[int, int, np.ndarray]] = []
        for bi, batch in enumerate(batches):
            row = dense_cache.get(batch.rq_id)
            if row is None:
                variants = rq_map.get_variants(batch.rq_id).variants
                k = len(variants)
                nd = np.zeros((k, n_r), dtype=np.int64)
                am = np.zeros((k, n_r), dtype=np.int32)
                mt = np.empty(k, dtype=np.int32)
                for vi, variant in enumerate(variants):
                    mt[vi] = min(int(variant.min_time_secs), int(INF_TIME))
                    for entry in variant.entries:
                        if entry.policy is AllocationPolicy.ALL:
                            am[vi, entry.resource_id] = 1
                        else:
                            nd[vi, entry.resource_id] = entry.amount
                wt = np.array([v.weight for v in variants], dtype=np.float64)
                row = (k, nd, am if am.any() else None, mt,
                       wt if (wt != 1.0).any() else None)
                dense_cache[batch.rq_id] = row
            k, nd, am, mt, wt = row
            needs[bi, :k] = nd
            min_time[bi, :k] = mt
            if am is not None and all_mask is not None:
                all_mask[bi, :k] = am
            if wt is not None:
                weighted_rows.append((bi, k, wt))
        w_arr = None
        if weighted_rows:
            # request weights (from the dense cache — only classes that
            # carry a non-default weight appear): the greedy model already
            # consumed them through the batch-order objective; the MILP
            # folds them into its own
            w_arr = np.ones((n_b, n_v), dtype=np.float64)
            for bi, k, wt in weighted_rows:
                w_arr[bi, :k] = wt
        needs_cache_owned = False
        if key_cache is not None:
            key_cache.batch_layout_sig = layout_sig
            key_cache.batch_layout = {
                "needs64": needs,
                "min_time": min_time,
                "all_mask": all_mask,
                "w_arr": w_arr,
                "needs32": None,
            }
            needs_cache_owned = True  # stored: shifts must copy-on-write

    shifts = _compress_shifts(needs, free, total)
    any_shift = any(shifts)
    if any_shift:
        # a shift mutates arrays in place — never the cache-owned
        # persistent ones (the common no-shift tick copies nothing)
        if cache_owns_arrays:
            free = free.copy()
            if total is not None:
                total = total.copy()
        if needs_cache_owned:
            needs = needs.copy()
    _apply_compression(shifts, needs, free, total)
    free32 = free.astype(np.int32)
    if not any_shift and needs_cache_owned and key_cache is not None:
        needs32 = key_cache.batch_layout["needs32"]
        if needs32 is None:
            needs32 = needs.astype(np.int32)
            key_cache.batch_layout["needs32"] = needs32
    else:
        needs32 = needs.astype(np.int32)
    extra = {}
    if all_mask is not None and all_mask.any():
        extra = {"total": total.astype(np.int32), "all_mask": all_mask}
    if w_arr is not None:
        extra["weights"] = w_arr
    if policy is not None and policy.rows:
        # heterogeneity affinity (B, W): one row per batch in the SORTED
        # order, index-aligned with the solve's worker axis.  Classes the
        # policy does not name keep a flat 1.0 row; distinct from the
        # (B, V) request-weight `weights` input above.
        aff = None
        for bi, b in enumerate(batches):
            row = policy.affinity_for(b.rq_id)
            if row is None:
                continue
            if aff is None:
                aff = np.ones((n_b, n_w), dtype=np.float32)
            aff[bi, : min(len(row), n_w)] = row[:n_w]
        if aff is not None:
            extra["affinity"] = aff
    if any(b.gang_nodes for b in batches):
        # fused gang rows: per-batch gang sizes plus the worker-side
        # idleness/group inputs the kernel's all-or-nothing selection needs
        extra["gang_nodes"] = np.fromiter(
            (b.gang_nodes for b in batches), dtype=np.int32, count=n_b
        )
        extra["gang_ok"] = (
            np.zeros(n_w, dtype=np.int32) if gang_ok is None
            else np.asarray(gang_ok, dtype=np.int32)
        )
        gids = (
            np.zeros(n_w, dtype=np.int32) if group_ids is None
            else np.asarray(group_ids, dtype=np.int32)
        )
        n_g = int(gids.max(initial=0)) + 1
        extra["group_onehot"] = (
            gids[:, None] == np.arange(n_g, dtype=np.int32)[None, :]
        ).astype(np.int32)
    if cpu_floor is not None:
        # joint mu path (run_tick): if _range_compress shifted the cpu
        # column, ceil-shift the floors the same way (a floor must never
        # become EASIER to meet than the unshifted program)
        if shifts[0]:
            s = shifts[0]
            cpu_floor = (cpu_floor + (1 << s) - 1) >> s
        extra["cpu_floor"] = cpu_floor
    return {
        "free": free32,
        "nt_free": nt_free,
        "lifetime": lifetime,
        "needs": needs32,
        "sizes": sizes,
        "min_time": min_time,
        "priorities": [b.priority for b in batches],
        **extra,
    }


def _run_main_solve(queues, workers, rq_map, resource_map, model, batches,
                    cpu_floor=None, dense=None, phases=None, key_cache=None,
                    decision=None, pipeline=None, gang_ok=None,
                    group_ids=None, policy=None):
    _t0 = _time.perf_counter()
    kwargs = assemble_solve_inputs(
        workers, batches, rq_map, resource_map, cpu_floor=cpu_floor,
        dense=dense, key_cache=key_cache, gang_ok=gang_ok,
        group_ids=group_ids, policy=policy,
    )
    _t1 = _time.perf_counter()
    if pipeline is not None and hasattr(model, "solve_async"):
        # pipelined dispatch: enqueue the solve and return WITHOUT mapping
        # — the caller maps this solve at the top of its next tick
        # (pipeline.take_result), after the device had the whole inter-tick
        # window to execute.  Only reachable on the dense path (run_tick),
        # where worker_ids come from the snapshot.
        from hyperqueue_tpu.scheduler.pipeline import PendingSolve

        handle = model.solve_async(**kwargs)
        if phases is not None:
            phases["assemble"] = (
                phases.get("assemble", 0.0) + (_t1 - _t0) * 1e3
            )
            phases["solve_dispatch"] = (
                phases.get("solve_dispatch", 0.0)
                + (_time.perf_counter() - _t1) * 1e3
            )
        if decision is not None:
            decision.setdefault("solver", {
                "status": "pipelined",
                "backend": getattr(model, "last_backend", None),
                "backend_reason": getattr(model, "last_backend_reason", ""),
                "pipelined": True,
            })
        pipeline.put(PendingSolve(
            handle=handle,
            batches=batches,
            worker_ids=list(dense.worker_ids),
            queues=queues,
            backend=getattr(model, "last_backend", None),
            backend_reason=getattr(model, "last_backend_reason", ""),
        ))
        return []
    counts = model.solve(**kwargs)
    _t2 = _time.perf_counter()
    if decision is not None:
        # the solver's verdict for this tick's DecisionRecord
        # (scheduler/decision.py): a watchdog-wrapped model reports whether
        # THIS solve ran degraded/skipped; plain models are always "ok".
        # The objective mirrors the LP's maximized quantity in aggregate:
        # how many tasks the dense solve placed.
        if getattr(model, "last_solve_skipped", False):
            status = "skipped"
        elif getattr(model, "last_solve_degraded", False):
            status = "fallback"
        else:
            status = "ok"
        decision["solver"] = {
            "status": status,
            "backend": getattr(model, "last_backend", None),
            "backend_reason": getattr(model, "last_backend_reason", ""),
            "solve_ms": round((_t2 - _t1) * 1e3, 4),
            "objective": int(np.asarray(counts).sum()),
        }
    if phases is not None:
        phases["assemble"] = phases.get("assemble", 0.0) + (_t1 - _t0) * 1e3
        solve_ms = (_t2 - _t1) * 1e3
        # models that time their own dispatch/readback split report it
        # (greedy/multichip last_phases); the remainder is host-side
        # padding + visit-class prep inside solve()
        model_phases = getattr(model, "last_phases", None) or {}
        dispatch = model_phases.get("dispatch_ms", solve_ms)
        sync = model_phases.get("sync_ms", 0.0)
        phases["solve_dispatch"] = (
            phases.get("solve_dispatch", 0.0) + dispatch
        )
        phases["device_sync"] = phases.get("device_sync", 0.0) + sync
        phases["solve_host_prep"] = phases.get("solve_host_prep", 0.0) + max(
            solve_ms - dispatch - sync, 0.0
        )

    worker_ids = (
        dense.worker_ids if dense is not None
        else [w.worker_id for w in workers]
    )
    return _map_counts(queues, batches, worker_ids, counts, phases=phases)


def _map_counts(queues, batches, worker_ids, counts,
                phases=None) -> list[Assignment]:
    """Pop the solver's counts out of the queues as Assignment tuples.

    The one mapping path for the synchronous tick AND the pipelined tick
    (scheduler/pipeline.TickPipeline.take_result): `batches`/`worker_ids`
    are the solve-time snapshot, `queues` is live — a cell whose tasks
    were canceled (or stolen by prefill) while a pipelined solve was in
    flight simply pops fewer ids than the count, which is safe.

    Both backends hand over C-contiguous int32 counts (the device path
    slices the padded volume ON the device before readback —
    models/greedy._device_slicer), so the native nonzero fast path applies
    everywhere.
    """
    _t2 = _time.perf_counter()
    assignments: list[Assignment] = []
    counts = np.asarray(counts)
    try:
        # one global nonzero over (B, V, W): row-major order preserves the
        # per-batch FIFO take semantics of the nested loop it replaces
        from hyperqueue_tpu.utils.native import native_nonzero

        # both backends return contiguous int32 (host: padded-contiguous
        # native output; device: sliced on device before readback), so this
        # fast path is the common case on every backend now
        nz = (
            native_nonzero(counts)
            if counts.dtype == np.int32 and counts.flags.c_contiguous
            else None
        )
        if nz is not None:
            flat, vals = nz
            if flat.size == 0:
                return assignments
            bs, vs, ws = np.unravel_index(flat, counts.shape)
        else:
            bs, vs, ws = np.nonzero(counts)
            if bs.size == 0:
                return assignments
            vals = counts[bs, vs, ws]

        if any(b.gang_nodes for b in batches):
            # gang cells never touch the queues — the gang task lives in
            # the reactor's mn_queue until the assignment is applied.  Emit
            # one (gang_task, worker, rq, -1) sentinel per selected worker;
            # ordinary cells pop from queues fetched LAZILY (the eager
            # queues.queue() sweep below would auto-create empty queues for
            # the gang rq ids, silently registering them as single-node).
            extend = assignments.extend
            queue_by_bi: dict = {}
            for bi, vi, wi, n in zip(
                bs.tolist(), vs.tolist(), ws.tolist(), vals.tolist()
            ):
                batch = batches[bi]
                if batch.gang_nodes:
                    assignments.append(
                        (batch.gang_task, worker_ids[wi], batch.rq_id, -1)
                    )
                    continue
                queue = queue_by_bi.get(bi)
                if queue is None:
                    queue = queue_by_bi[bi] = queues.queue(batch.rq_id)
                task_ids = queue.take(batch.priority, n)
                worker_id = worker_ids[wi]
                extend(
                    [(task_id, worker_id, batch.rq_id, vi)
                     for task_id in task_ids]
                )
            return assignments

        batch_queues = [queues.queue(b.rq_id) for b in batches]
        native = _native_map_take(batch_queues, batches, bs, vals)
        extend = assignments.extend
        if native is not None:
            # one C call popped every cell's ids; stitch the tuples here
            # (slice + comprehension per cell: ~2x the indexed inner loop
            # at 16k+ assignments/tick)
            out_ids, cell_n = native
            pos = 0
            for ci, (bi, vi, wi) in enumerate(
                zip(bs.tolist(), vs.tolist(), ws.tolist())
            ):
                got = cell_n[ci]
                rq_id = batches[bi].rq_id
                worker_id = worker_ids[wi]
                end = pos + got
                extend(
                    [(tid, worker_id, rq_id, vi)
                     for tid in out_ids[pos:end]]
                )
                pos = end
            return assignments

        cur_bi = -1
        queue = rq_id = priority = None
        for bi, vi, wi, n in zip(
            bs.tolist(), vs.tolist(), ws.tolist(), vals.tolist()
        ):
            if bi != cur_bi:  # bs is sorted: hoist per-batch lookups per run
                cur_bi = bi
                batch = batches[bi]
                rq_id = batch.rq_id
                priority = batch.priority
                queue = batch_queues[bi]
            task_ids = queue.take(priority, n)
            worker_id = worker_ids[wi]
            extend(
                [(task_id, worker_id, rq_id, vi) for task_id in task_ids]
            )
        return assignments
    finally:
        if phases is not None:
            phases["mapping"] = phases.get("mapping", 0.0) + (
                _time.perf_counter() - _t2
            ) * 1e3


def _solve_mu_workers(queues, mu_rows, rq_map, resource_map):
    """Exact all-or-nothing solve for min-utilization workers (host side).

    Reference semantics (solver.rs:479-518 add_min_utilization): a worker
    with min_utilization either receives enough cpu work to push its busy
    cpus to at least mu x all_cpus, or receives no CPU-consuming work this
    tick (the constraint binds only cpu-consuming variables, so zero-cpu
    tasks — e.g. gpu-only — may land regardless). Per
    worker, a depth-first branch-and-bound over (request class, priority,
    variant) candidate counts maximizes the priority-lexicographic score
    (per level: task count, or weight x resource-share value when the level
    carries non-default request weights — mirroring the LP objective,
    solver.rs:520-549) subject to the worker's resources and the cpu floor.

    Candidates are capped at the 32 best (priority, value) classes and the
    search at MU_DFS_NODE_BUDGET nodes — past the budget the best fill
    found so far ships (usually the greedy first dive; possibly empty, in
    which case the worker stays idle this tick, a warning names it, and it
    retries next tick). mu workers are rare; exactness on small instances
    matters more than scale here.
    """
    from hyperqueue_tpu.resources.request import AllocationPolicy

    assignments: list[Assignment] = []
    n_r = len(resource_map)

    for row in sorted(mu_rows, key=lambda r: r.worker_id):
        free0 = list(row.free[:n_r]) + [0] * (n_r - len(row.free))
        total0 = list((row.total or row.free)[:n_r])
        total0 += [0] * (n_r - len(total0))
        floor = row.cpu_floor
        nt0 = max(row.nt_free, 0)
        if nt0 == 0:
            continue

        # --- gather candidates from the current queue state ---
        # group = (rq_id, priority): variants of one class share the queued
        # count, so the DFS constrains the SUM of their takes (mirrors the
        # kernel's one `remaining` across the variant axis in scan_batches)
        cands = []  # (priority, value, rq_id, vi, needs(R,), max_count, grp)
        group_count: dict[tuple[int, tuple], int] = {}
        for rq_id, queue in queues.items():
            rqv = rq_map.get_variants(rq_id)
            if rqv.is_multi_node:
                continue
            for priority, count in queue.priority_sizes():
                if count <= 0:
                    continue
                group_count[(rq_id, priority)] = count
                for vi, variant in enumerate(rqv.variants):
                    if variant.min_time_secs > row.lifetime_secs:
                        continue
                    needs_vec = [0] * n_r
                    ok = True
                    for e in variant.entries:
                        if e.resource_id >= n_r:
                            ok = False
                            break
                        amt = (
                            total0[e.resource_id]
                            if e.policy is AllocationPolicy.ALL
                            else e.amount
                        )
                        if e.policy is AllocationPolicy.ALL and (
                            amt <= 0 or free0[e.resource_id] != amt
                        ):
                            ok = False
                            break
                        needs_vec[e.resource_id] = amt
                    if not ok:
                        continue
                    fit = nt0
                    for r in range(n_r):
                        if needs_vec[r] > 0:
                            fit = min(fit, free0[r] // needs_vec[r])
                    if fit <= 0:
                        continue
                    value = variant.weight * sum(
                        needs_vec[r] / total0[r]
                        for r in range(n_r)
                        if needs_vec[r] > 0 and total0[r] > 0
                    )
                    cands.append(
                        (priority, value, rq_id, vi, needs_vec,
                         min(count, fit), (rq_id, priority))
                    )
        if not cands:
            continue
        cands.sort(key=lambda c: (c[0], c[1]), reverse=True)
        cands = cands[:32]
        group_left0 = dict(group_count)

        # priority levels and their scoring mode (count vs weighted value)
        levels = sorted({c[0] for c in cands}, reverse=True)
        level_of = {p: i for i, p in enumerate(levels)}
        weighted_level = [False] * len(levels)
        for c in cands:
            if abs(rq_map.get_variants(c[2]).variants[c[3]].weight - 1.0) \
                    > 1e-9:
                weighted_level[level_of[c[0]]] = True

        def task_score(c):
            return c[1] if weighted_level[level_of[c[0]]] else 1.0

        # optimistic per-level remaining score from candidate i onward
        n_c = len(cands)
        opt = [[0.0] * len(levels) for _ in range(n_c + 1)]
        for i in range(n_c - 1, -1, -1):
            opt[i] = list(opt[i + 1])
            c = cands[i]
            opt[i][level_of[c[0]]] += task_score(c) * c[5]

        # static suffix bound on addable cpus (ignores shared resources:
        # an over-estimate, which is what a prune needs)
        suffix_cpu = [0] * (n_c + 1)
        for i in range(n_c - 1, -1, -1):
            suffix_cpu[i] = suffix_cpu[i + 1] + cands[i][4][0] * cands[i][5]

        best_score: list[float] | None = None
        best_take: list[int] | None = None
        nodes = 0

        def dfs(i, free, nt, cpu_used, score, take):
            nonlocal best_score, best_take, nodes
            nodes += 1
            if nodes > MU_DFS_NODE_BUDGET:
                return
            # prune: even everything remaining cannot beat the best
            if best_score is not None:
                bound = [s + o for s, o in zip(score, opt[i])]
                if bound <= best_score:
                    return
            # prune: floor unreachable even with all remaining cpus (only
            # once cpus are committed — an all-zero-cpu completion stays
            # feasible from cpu_used == 0)
            if 0 < cpu_used and cpu_used + suffix_cpu[i] < floor:
                return
            if i == n_c:
                # all-or-nothing applies to CPU usage (reference
                # solver.rs:479-518 constrains only cpu-consuming variables):
                # zero-cpu assignments (e.g. gpu-only tasks) are always
                # allowed on a floored worker
                if (cpu_used == 0 or cpu_used >= floor) and (
                    best_score is None or score > best_score
                ):
                    best_score = list(score)
                    best_take = list(take)
                return
            c = cands[i]
            needs_vec = c[4]
            x_max = min(c[5], nt, group_left[c[6]])
            for r in range(n_r):
                if needs_vec[r] > 0:
                    x_max = min(x_max, free[r] // needs_vec[r])
            for x in range(x_max, -1, -1):
                if x:
                    new_free = [
                        free[r] - x * needs_vec[r] for r in range(n_r)
                    ]
                else:
                    new_free = free
                li = level_of[c[0]]
                new_score = list(score)
                new_score[li] += task_score(c) * x
                take.append(x)
                group_left[c[6]] -= x
                dfs(
                    i + 1, new_free, nt - x,
                    cpu_used + x * needs_vec[0], new_score, take,
                )
                group_left[c[6]] += x
                take.pop()

        group_left = dict(group_left0)
        dfs(0, free0, nt0, 0, [0.0] * len(levels), [])

        if nodes > MU_DFS_NODE_BUDGET:
            # budget exhausted: the best solution FOUND so far still ships
            # (the first dive is a greedy max-take seed, so one is almost
            # always in hand); log so an idle mu worker is explainable
            import logging

            logging.getLogger(__name__).warning(
                "min-utilization solve for worker %d hit the %d-node "
                "budget; shipping the best fill found (%s)",
                row.worker_id, MU_DFS_NODE_BUDGET,
                "non-empty" if best_take and any(best_take) else "empty",
            )
        if not best_take or not any(best_take):
            continue
        for c, x in zip(cands, best_take):
            if x <= 0:
                continue
            priority, _value, rq_id, vi = c[0], c[1], c[2], c[3]
            for task_id in queues.queue(rq_id).take(priority, x):
                assignments.append((task_id, row.worker_id, rq_id, vi))
    return assignments


def _native_map_take(batch_queues, batches, bs, vals):
    """Pop every solver cell's task ids with ONE native call when all batch
    queues are C++-backed (native/hqcore.cpp hq_map_take); returns
    (ids_list, per_cell_counts) or None to use the per-cell Python path."""
    import ctypes

    from hyperqueue_tpu.utils.native import NativeTaskQueue

    if not all(isinstance(q, NativeTaskQueue) for q in batch_queues):
        return None
    lib = batch_queues[0]._lib
    n_b = len(batches)
    handles = (ctypes.c_void_p * n_b)(
        *(q._handle for q in batch_queues)
    )
    pu = (ctypes.c_int64 * n_b)(*(b.priority[0] for b in batches))
    ps = (ctypes.c_int64 * n_b)(*(b.priority[1] for b in batches))
    n_cells = bs.size
    # hand the solver's ndarrays to C directly — building ctypes arrays
    # element-by-element was ~1 ms/tick at 1M x 1k
    cell_batch = np.ascontiguousarray(bs, dtype=np.int64)
    cell_count = np.ascontiguousarray(vals, dtype=np.int64)
    max_ids = int(cell_count.sum())
    out_ids = np.empty(max_ids, dtype=np.uint64)
    cell_n = np.empty(n_cells, dtype=np.int64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.hq_map_take(
        handles, pu, ps,
        cell_batch.ctypes.data_as(i64p),
        cell_count.ctypes.data_as(i64p),
        n_cells,
        out_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        cell_n.ctypes.data_as(i64p),
    )
    return out_ids.tolist(), cell_n.tolist()
