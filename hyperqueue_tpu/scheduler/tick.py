"""One scheduling tick: queues -> batches -> dense snapshot -> solve -> mapping.

Reference factoring: crates/tako/src/internal/scheduler/main.rs:40-46
(batches -> solver -> mapping). The dense snapshot is the seam where the work
moves to the TPU: everything up to `model.solve` is host bookkeeping over
dicts; the solve itself sees only integer tensors (SURVEY.md §3.2).

Batches: per rq-id queue, each distinct priority level becomes a cut, capped
at MAX_CUTS_PER_QUEUE with the tail merged into the last cut (reference
batches.rs:183-217 prunes similarly). Batches from all queues are solved
jointly, globally ordered by priority.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from hyperqueue_tpu.utils.constants import INF_TIME
from hyperqueue_tpu.resources.map import ResourceIdMap, ResourceRqMap
from hyperqueue_tpu.scheduler.queues import Priority, TaskQueues

MAX_CUTS_PER_QUEUE = 32
# Values above this get range-compressed before entering the kernel — the
# kernel requires amounts to be float32-exact (ops/assign.MAX_KERNEL_AMOUNT).
MAX_SAFE_AMOUNT = 2**23


@dataclass(slots=True)
class Batch:
    rq_id: int
    priority: Priority
    size: int


@dataclass(slots=True)
class WorkerRow:
    worker_id: int
    free: list[int]       # dense fractions, aligned to ResourceIdMap
    nt_free: int
    lifetime_secs: int    # INF_TIME if unlimited


# One assignment is a plain (task_id, worker_id, rq_id, variant) tuple:
# at 16k+ assignments per tick, object construction dominated the mapping
# phase (dataclass/NamedTuple are ~5x slower to build than tuples).
Assignment = tuple[int, int, int, int]


def create_batches(queues: TaskQueues) -> list[Batch]:
    batches: list[Batch] = []
    for rq_id, queue in queues.items():
        sizes = queue.priority_sizes()
        if len(sizes) > MAX_CUTS_PER_QUEUE:
            head = sizes[: MAX_CUTS_PER_QUEUE - 1]
            tail_count = sum(n for _, n in sizes[MAX_CUTS_PER_QUEUE - 1 :])
            tail_priority = sizes[MAX_CUTS_PER_QUEUE - 1][0]
            sizes = head + [(tail_priority, tail_count)]
        for priority, count in sizes:
            batches.append(Batch(rq_id=rq_id, priority=priority, size=count))
    batches.sort(key=lambda b: (b.priority, -b.rq_id), reverse=True)
    return batches


def _range_compress(needs: np.ndarray, free: np.ndarray) -> None:
    """Shift down any resource column whose values exceed int32-safe range.

    needs are ceil-shifted (request never shrinks to zero) and free floor-
    shifted, so feasibility decisions stay sound (never optimistic).
    """
    for r in range(free.shape[1]):
        peak = max(
            int(free[:, r].max(initial=0)), int(needs[:, :, r].max(initial=0))
        )
        shift = 0
        while (peak >> shift) >= MAX_SAFE_AMOUNT:
            shift += 1
        if shift:
            nonzero = needs[:, :, r] > 0
            needs[:, :, r] = np.where(
                nonzero,
                np.maximum((needs[:, :, r] + (1 << shift) - 1) >> shift, 1),
                0,
            )
            free[:, r] >>= shift


def run_tick(
    queues: TaskQueues,
    workers: list[WorkerRow],
    rq_map: ResourceRqMap,
    resource_map: ResourceIdMap,
    model,
    batches: list[Batch] | None = None,
) -> list[Assignment]:
    """Solve one tick and pop assigned tasks from the queues.

    Removes assigned tasks from `queues`; does NOT touch worker resource
    accounting — the caller (reactor) applies each Assignment to its Worker
    state, which keeps one owner for the free/nt_free bookkeeping.

    `batches` lets the caller pass a precomputed create_batches(queues)
    result (the reactor builds it once per schedule() and reuses it for the
    prefill phase); the caller's list order is left untouched.
    """
    if batches is None:
        batches = create_batches(queues)
    else:
        batches = list(batches)  # sorted in place below; don't reorder caller
    if not batches or not workers:
        return []

    n_w = len(workers)
    n_r = len(resource_map)
    n_b = len(batches)
    n_v = max(
        len(rq_map.get_variants(b.rq_id).variants) for b in batches
    )

    free_lists = [row.free for row in workers]
    if all(len(f) == n_r for f in free_lists):
        # uniform rows (steady state): one C-level conversion instead of a
        # per-worker Python fill loop (~1.4 ms at 1k workers)
        free = np.array(free_lists, dtype=np.int64)
    else:
        # a worker's dense row can lag the global resource map right after
        # a new resource name is interned
        free = np.zeros((n_w, n_r), dtype=np.int64)
        for i, f in enumerate(free_lists):
            free[i, : len(f)] = f
    nt_free = np.fromiter(
        (row.nt_free if row.nt_free > 0 else 0 for row in workers),
        dtype=np.int32,
        count=n_w,
    )
    lifetime = np.fromiter(
        (row.lifetime_secs for row in workers), dtype=np.int32, count=n_w
    )

    # Most-constrained-first within a priority level: a class that can ONLY
    # run on scarce resources is placed before same-priority classes with
    # more options, so flexible work cannot strand the few workers carrying
    # a scarce pool (the reference MILP reaches the same outcome by solving
    # the level jointly, solver.rs; pinned by
    # test_scheduler_golden.test_gap_filling2_exact_class_counts).
    # Constrainedness is the MINIMUM over variants: a class with a
    # commodity-resource fallback is flexible no matter how scarce its
    # preferred variant is, and ordering it first would let its fallback
    # spill eat the common pool ahead of cheaper classes.
    # One scarcity notion for the whole solve (ops/assign.scarcity_weights,
    # also used for worker visit order): zero-capacity resources weigh 0
    # (an unschedulable class must not sort first), and free is clamped at 0
    # — over-commit from prefill races can drive worker free negative, like
    # the nt_free clamp above.
    from hyperqueue_tpu.ops.assign import scarcity_weights

    weights = scarcity_weights(np.maximum(free, 0).sum(axis=0))

    def _scarcity(batch: Batch) -> float:
        score = float("inf")
        for variant in rq_map.get_variants(batch.rq_id).variants:
            v_score = 0.0
            for entry in variant.entries:
                if entry.amount > 0 and entry.resource_id < n_r:
                    s = float(weights[entry.resource_id])
                    if s > v_score:
                        v_score = s
            if v_score < score:
                score = v_score
        return 0.0 if score == float("inf") else score

    batches.sort(key=lambda b: (b.priority, _scarcity(b)), reverse=True)

    needs = np.zeros((n_b, n_v, n_r), dtype=np.int64)
    sizes = np.zeros(n_b, dtype=np.int32)
    min_time = np.zeros((n_b, n_v), dtype=np.int32)
    min_time[:] = int(INF_TIME)  # absent variants never eligible
    for bi, batch in enumerate(batches):
        sizes[bi] = min(batch.size, 2**30)
        variants = rq_map.get_variants(batch.rq_id).variants
        for vi, variant in enumerate(variants):
            min_time[bi, vi] = min(int(variant.min_time_secs), int(INF_TIME))
            for entry in variant.entries:
                needs[bi, vi, entry.resource_id] = entry.amount

    _range_compress(needs, free)
    free32 = free.astype(np.int32)
    counts = model.solve(
        free=free32,
        nt_free=nt_free,
        lifetime=lifetime,
        needs=needs.astype(np.int32),
        sizes=sizes,
        min_time=min_time,
        priorities=[b.priority for b in batches],
    )

    assignments: list[Assignment] = []
    counts = np.asarray(counts)
    # one global nonzero over (B, V, W): row-major order preserves the
    # per-batch FIFO take semantics of the nested loop it replaces
    bs, vs, ws = np.nonzero(counts)
    if bs.size == 0:
        return assignments
    vals = counts[bs, vs, ws]

    batch_queues = [queues.queue(b.rq_id) for b in batches]
    native = _native_map_take(batch_queues, batches, bs, vals)
    append = assignments.append
    if native is not None:
        # one C call popped every cell's ids; stitch the tuples here
        out_ids, cell_n = native
        pos = 0
        for ci, (bi, vi, wi) in enumerate(
            zip(bs.tolist(), vs.tolist(), ws.tolist())
        ):
            got = cell_n[ci]
            rq_id = batches[bi].rq_id
            worker_id = workers[wi].worker_id
            for k in range(pos, pos + got):
                append((out_ids[k], worker_id, rq_id, vi))
            pos += got
        return assignments

    cur_bi = -1
    queue = rq_id = priority = None
    for bi, vi, wi, n in zip(
        bs.tolist(), vs.tolist(), ws.tolist(), vals.tolist()
    ):
        if bi != cur_bi:  # bs is sorted: hoist per-batch lookups per run
            cur_bi = bi
            batch = batches[bi]
            rq_id = batch.rq_id
            priority = batch.priority
            queue = batch_queues[bi]
        task_ids = queue.take(priority, n)
        worker_id = workers[wi].worker_id
        for task_id in task_ids:
            append((task_id, worker_id, rq_id, vi))
    return assignments


def _native_map_take(batch_queues, batches, bs, vals):
    """Pop every solver cell's task ids with ONE native call when all batch
    queues are C++-backed (native/hqcore.cpp hq_map_take); returns
    (ids_list, per_cell_counts) or None to use the per-cell Python path."""
    import ctypes

    from hyperqueue_tpu.utils.native import NativeTaskQueue

    if not all(isinstance(q, NativeTaskQueue) for q in batch_queues):
        return None
    lib = batch_queues[0]._lib
    n_b = len(batches)
    handles = (ctypes.c_void_p * n_b)(
        *(q._handle for q in batch_queues)
    )
    pu = (ctypes.c_int64 * n_b)(*(b.priority[0] for b in batches))
    ps = (ctypes.c_int64 * n_b)(*(b.priority[1] for b in batches))
    n_cells = bs.size
    cell_batch = (ctypes.c_int64 * n_cells)(*bs.tolist())
    cell_count = (ctypes.c_int64 * n_cells)(*vals.tolist())
    max_ids = int(vals.sum())
    out_ids = (ctypes.c_uint64 * max_ids)()
    cell_n = (ctypes.c_int64 * n_cells)()
    lib.hq_map_take(
        handles, pu, ps, cell_batch, cell_count, n_cells, out_ids, cell_n
    )
    return list(out_ids), list(cell_n)
