"""Two-stage asynchronous tick pipeline.

The synchronous tick pays the device round trip on its critical path:
assemble -> dispatch -> BLOCK on readback -> map.  With the device-resident
state (parallel/resident.py) the solve's inputs live on the accelerator, so
the host has no reason to wait: tick k DISPATCHES solve k and immediately
maps the counts of solve k-1 (whose device execution overlapped all the
host work since the previous tick — applying assignments, journal writes,
network IO).  The readback at the top of tick k almost always finds the
result already materialized, so the device round trip disappears from the
tick's critical path entirely.

Semantics: assignments lag one tick (solve k's placements are applied at
tick k+1).  This is safe because the solve is pure — worker state advances
on the DEVICE via donated free_after/nt_after, the host applies the same
deltas when it maps, and anything else that moved in between (completions,
new submits) reaches the device as next tick's dirty rows.  Mapped task
ids are popped from the live queues at map time: a task canceled while its
solve was in flight is simply no longer there to pop, and the counts cell
comes up short harmlessly.  Workers that disconnected in flight are
filtered by the reactor (their tasks go back to the queues).

The pipeline is OPT-IN (`hq server start --tick-pipeline`) and degrades to
the synchronous path whenever exactness tooling or fault handling needs
it: `--paranoid-tick` ticks force a drain + synchronous solve, and the
solver watchdog drains the pipeline before any fallback solve (a pending
handle that fails or times out is itself resolved by the watchdog's
fallback — see scheduler/watchdog.py).
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from hyperqueue_tpu.utils import clock


@dataclass(slots=True)
class PendingSolve:
    """One dispatched-but-unmapped solve."""

    handle: object            # .result() -> unpadded counts (B, V, W)
    batches: list             # solve-ordered batches at dispatch time
    worker_ids: list          # row -> worker_id at dispatch time
    queues: object            # TaskQueues to pop from at map time
    backend: str | None       # model.last_backend at dispatch
    backend_reason: str       # model.last_backend_reason at dispatch
    dispatched_at: float = field(default_factory=_time.perf_counter)
    # wall-clock dispatch stamp: the Perfetto export places the pipelined
    # solve's execution window by these recorded stamps instead of charging
    # it to the tick that happens to MAP it (PR 8 satellite)
    dispatched_wall: float = field(default_factory=clock.now)
    # (membership_epoch, queues.version, total_ready) at dispatch: the
    # reactor stamps it and, when this solve maps EMPTY and the signature
    # still matches (and no worker row moved), skips re-dispatching — an
    # unplaceable backlog must not spin the scheduler at min-delay cadence
    state_sig: tuple | None = None


class TickPipeline:
    """Holds at most one in-flight solve between reactor ticks."""

    def __init__(self) -> None:
        self.pending: PendingSolve | None = None
        # dispatch-time signature of the last solve that mapped EMPTY
        # (None once any solve maps assignments): while the live state
        # still matches it, re-solving is provably redundant and the
        # reactor skips the dispatch — see PendingSolve.state_sig
        self.idle_sig: tuple | None = None
        # telemetry (hq server stats / metrics collect hook)
        self.dispatched = 0
        self.mapped = 0
        self.drains = 0
        self.last_wait_ms = 0.0

    @property
    def depth(self) -> int:
        return 1 if self.pending is not None else 0

    def put(self, pending: PendingSolve) -> None:
        assert self.pending is None, "tick pipeline depth is 1"
        self.pending = pending
        self.dispatched += 1

    def take_result(self, model=None, phases: dict | None = None,
                    decision: dict | None = None) -> list:
        """Materialize and map the pending solve; returns its assignments.

        The wait for the device result is timed separately
        (`pipeline_wait` phase): in steady state it is ~zero because the
        device ran during the inter-tick host work."""
        from hyperqueue_tpu.scheduler.tick import _map_counts

        pending = self.pending
        if pending is None:
            return []
        self.pending = None
        _t0 = _time.perf_counter()
        counts = pending.handle.result()
        _t1 = _time.perf_counter()
        self.last_wait_ms = (_t1 - _t0) * 1e3
        if phases is not None:
            phases["pipeline_wait"] = (
                phases.get("pipeline_wait", 0.0) + self.last_wait_ms
            )
        if decision is not None:
            import numpy as np

            if model is not None and getattr(
                model, "last_solve_skipped", False
            ):
                status = "skipped"
            elif model is not None and getattr(
                model, "last_solve_degraded", False
            ):
                status = "fallback"
            else:
                status = "ok"
            decision["solver"] = {
                "status": status,
                "backend": pending.backend,
                "backend_reason": pending.backend_reason,
                "pipelined": True,
                # the solve cost the TICK paid is the readback wait — the
                # execution itself overlapped inter-tick host work;
                # inflight_ms (dispatch -> map, including server idle) is
                # kept separately for context
                "solve_ms": round(self.last_wait_ms, 4),
                "inflight_ms": round((_t1 - pending.dispatched_at) * 1e3, 1),
                # recorded dispatch/readback wall stamps: the trace export
                # renders the solve where it actually EXECUTED
                "dispatched_at_wall": pending.dispatched_wall,
                "mapped_at_wall": clock.now(),
                "objective": int(np.asarray(counts).sum()),
            }
        assignments = _map_counts(
            pending.queues, pending.batches, pending.worker_ids, counts,
            phases=phases,
        )
        self.mapped += 1
        self.idle_sig = pending.state_sig if not assignments else None
        return assignments

    def drain(self, model=None, phases: dict | None = None,
              decision: dict | None = None) -> list:
        """take_result, counted as a forced drain (paranoid tick, watchdog
        fallback, mu-worker tick, shutdown)."""
        if self.pending is not None:
            self.drains += 1
        return self.take_result(model=model, phases=phases,
                                decision=decision)

    def stats(self) -> dict:
        return {
            "depth": self.depth,
            "dispatched": self.dispatched,
            "mapped": self.mapped,
            "drains": self.drains,
            "last_wait_ms": round(self.last_wait_ms, 3),
        }
