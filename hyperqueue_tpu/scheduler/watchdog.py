"""Solver watchdog: the scheduling loop must survive a misbehaving solver.

A single exception or hang inside the per-tick solve (JAX MILP, the jitted
greedy kernel, or a wedged device relay) previously killed the scheduler
loop — the server kept accepting submits but never scheduled again.
Dynamic schedulers must degrade gracefully rather than stop scheduling
when the optimizer misbehaves (arXiv:1106.4985); long-running cluster
workloads are exactly where component failure dominates (arXiv:2008.09213).

The watchdog wraps any scheduling model:

- every primary solve runs on a dedicated daemon thread with a wall-clock
  deadline (``timeout_s``); a hang strands that thread (abandoned, daemon)
  and the tick proceeds without it;
- an exception or timeout degrades the tick to a host-side greedy
  assignment (GreedyCutScanModel, numpy backend) and benches the primary;
- after ``rearm_ticks`` clean fallback ticks the primary is re-armed and
  tried again — a transient failure self-heals, a persistent one keeps the
  server scheduling on the fallback indefinitely;
- if the fallback ALSO fails, the tick assigns nothing (zero counts) and
  the server stays alive to try again next tick.

Degradation is visible: counters (failures, timeouts, degraded ticks,
re-arms) are surfaced through ``hq server stats`` (see
Server._client_server_stats).
"""

from __future__ import annotations

import logging
import queue as _queue
import threading
import time

import numpy as np

from hyperqueue_tpu.utils import chaos

logger = logging.getLogger("hq.watchdog")

DEFAULT_TIMEOUT_S = 5.0
DEFAULT_REARM_TICKS = 20


class SolveTimeout(RuntimeError):
    pass


class _SolveWorker:
    """One daemon thread executing solves so a hang cannot wedge the server
    event loop. A timed-out solve strands the thread mid-call; the watchdog
    abandons the whole worker (daemon threads never block process exit) and
    builds a fresh one for the next primary attempt. A late result from an
    abandoned thread lands in a result box nobody reads — solves are pure,
    so discarding it is safe."""

    def __init__(self):
        self._requests: _queue.Queue = _queue.Queue()
        # done-event of the most recent request: after a timeout it tells
        # whether the stranded thread is STILL inside the solve
        self.last_done: threading.Event | None = None
        self._thread = threading.Thread(
            target=self._loop, name="hq-solve-watchdog", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        from hyperqueue_tpu.utils import profiler

        # sampling-profiler plane label (ISSUE 19): deadlined solves run
        # here, so solver CPU attributes to the `solve` plane even while
        # the reactor thread is parked in done.wait(). The label is never
        # explicitly unregistered — an abandoned (stranded) worker keeps
        # soaking CPU inside the solve, and THAT is exactly what the
        # profile must show; the thread-name prefix fallback re-labels
        # any replacement worker anyway.
        profiler.register_plane("solve")
        while True:
            fn, box, done = self._requests.get()
            try:
                box["result"] = fn()
            except BaseException as e:  # noqa: BLE001 - ferried to the caller
                box["error"] = e
            done.set()

    def run(self, fn, timeout: float):
        box: dict = {}
        done = threading.Event()
        self.last_done = done
        self._requests.put((fn, box, done))
        if not done.wait(timeout):
            raise SolveTimeout(
                f"solve exceeded the {timeout:g}s watchdog deadline"
            )
        if "error" in box:
            raise box["error"]
        return box["result"]


class SolverWatchdog:
    """Wraps a scheduling model with an exception guard + solve deadline.

    Drop-in for the model protocol the tick uses (solve /
    supports_cpu_floor / last_backend / last_phases ...); unknown
    attributes delegate to whichever model ran the last solve, so
    telemetry (shape_allocations, last_phases) stays truthful in degraded
    mode.
    """

    def __init__(
        self,
        model,
        timeout_s: float = DEFAULT_TIMEOUT_S,
        rearm_ticks: int = DEFAULT_REARM_TICKS,
        fallback=None,
    ):
        # set _last_ran FIRST: __getattr__ delegates through it
        self._last_ran = model
        self.model = model
        if fallback is None:
            from hyperqueue_tpu.models.greedy import GreedyCutScanModel

            fallback = GreedyCutScanModel(backend="numpy")
        self.fallback = fallback
        self.timeout_s = timeout_s
        self.rearm_ticks = max(int(rearm_ticks), 1)
        self._bench_remaining = 0  # fallback ticks left before re-arming
        # bench window elapsed but a stranded solve blocked the re-arm:
        # count/log the re-arm at the first primary attempt after it drains
        self._rearm_pending = False
        self._worker: _SolveWorker | None = None
        # done-events of timed-out solves whose threads may still be
        # executing inside the (stateful) primary model
        self._abandoned: list = []
        self.failures = 0
        self.timeouts = 0
        self.degraded_ticks = 0
        self.rearms = 0
        self.skipped_ticks = 0
        self.last_error = ""
        # per-solve verdict of the MOST RECENT solve() call, read by the
        # tick's DecisionRecord (scheduler/decision.py): degraded = the
        # fallback ran this tick, skipped = even the fallback failed
        self.last_solve_degraded = False
        self.last_solve_skipped = False

    # --- model protocol -------------------------------------------------
    def _abandoned_busy(self) -> bool:
        """Is a timed-out solve still executing inside the primary model?
        Its thread shares the model's persistent buffers, so the primary
        may not run again until it drains."""
        if self._abandoned:
            self._abandoned = [e for e in self._abandoned if not e.is_set()]
        return bool(self._abandoned)

    @property
    def armed(self) -> bool:
        return self._bench_remaining == 0 and not self._abandoned_busy()

    @property
    def supports_cpu_floor(self) -> bool:
        # while benched, the greedy fallback runs the tick — it cannot
        # express the joint min-utilization floor, so the tick must use the
        # host-side mu carve-out instead
        return self.armed and getattr(self.model, "supports_cpu_floor", False)

    def __getattr__(self, name):
        # only reached for attributes not set on the watchdog itself
        return getattr(object.__getattribute__(self, "_last_ran"), name)

    def reset_stats(self) -> None:
        """Zero the telemetry counters (reset_metrics debug RPC) without
        touching the armed/bench state machine."""
        self.failures = 0
        self.timeouts = 0
        self.degraded_ticks = 0
        self.rearms = 0
        self.skipped_ticks = 0

    def stats(self) -> dict:
        return {
            "armed": self.armed,
            "bench_remaining": self._bench_remaining,
            "failures": self.failures,
            "timeouts": self.timeouts,
            "degraded_ticks": self.degraded_ticks,
            "rearms": self.rearms,
            "skipped_ticks": self.skipped_ticks,
            "timeout_s": self.timeout_s,
            "rearm_ticks": self.rearm_ticks,
            "last_error": self.last_error,
        }

    # --- solve ----------------------------------------------------------
    def solve(self, **kwargs) -> np.ndarray:
        self.last_solve_degraded = False
        self.last_solve_skipped = False
        # not armed (benched, or a stranded solve still runs) falls through
        # to _run_fallback below
        if self.armed:
            if self._rearm_pending:
                self._rearm_pending = False
                self.rearms += 1
                logger.warning(
                    "re-arming the primary solver (stranded solve drained)"
                )
            try:
                result = self._run_primary(kwargs)
                self._last_ran = self.model
                return result
            except SolveTimeout as e:
                self.timeouts += 1
                self._degrade(e)
            except Exception as e:  # noqa: BLE001 - the guard IS the point
                self._raise_if_paranoid(e)
                self.failures += 1
                self._degrade(e)
        return self._run_fallback(kwargs)

    @staticmethod
    def _raise_if_paranoid(error: BaseException) -> None:
        """--paranoid-tick contract violations must be LOUD, like
        tick_cache.paranoid_check: degrading would both hide the bug and
        destroy the evidence (the degrade path invalidates the resident
        state the divergence lives in)."""
        from hyperqueue_tpu.models.greedy import ResidentParanoidError

        if isinstance(error, ResidentParanoidError):
            raise error

    def _degrade(self, error: BaseException) -> None:
        self.last_error = f"{type(error).__name__}: {error}"
        self._bench_remaining = self.rearm_ticks
        # a failed/abandoned solve may have consumed (donated) or half-
        # updated the primary's device-resident tick state: drop it so the
        # next primary attempt starts from a clean full upload
        invalidate = getattr(self.model, "invalidate_resident", None)
        if invalidate is not None:
            try:
                invalidate()
            except Exception:  # noqa: BLE001 - never raise out of degrade
                pass
        logger.critical(
            "solver failed (%s); degrading to the host greedy fallback for "
            "%d ticks",
            self.last_error, self.rearm_ticks,
            exc_info=not isinstance(error, SolveTimeout),
        )

    def _run_primary(self, kwargs):
        def call():
            if chaos.ACTIVE:
                # poisoned-solve injection runs INSIDE the guarded call so
                # a "hang" exercises the deadline machinery, not the loop
                chaos.fire("solve")
            return self.model.solve(**kwargs)

        return self._run_deadlined(call)

    def _run_deadlined(self, call):
        """Run `call` on the watchdog thread under the solve deadline."""
        if self.timeout_s <= 0:
            return call()  # exception guard only
        if self._worker is None:
            self._worker = _SolveWorker()
        try:
            return self._worker.run(call, self.timeout_s)
        except SolveTimeout:
            # the thread is wedged inside the solve: abandon it (daemon)
            if self._worker.last_done is not None:
                self._abandoned.append(self._worker.last_done)
            self._worker = None
            raise

    # --- async solve (the pipelined tick, scheduler/pipeline.py) ---------
    def solve_async(self, **kwargs):
        """Guarded async dispatch: returns a handle whose `.result()` is
        ALSO guarded — an exception or deadline overrun while materializing
        the pending counts degrades exactly like a synchronous failure
        (bench the primary, drop its resident device state, and solve the
        SAME snapshot on the host fallback), so a pipelined tick can never
        lose a solve: the pipeline's pending handle always resolves to a
        valid counts array."""
        self.last_solve_degraded = False
        self.last_solve_skipped = False
        if self.armed and hasattr(self.model, "solve_async"):
            if self._rearm_pending:
                self._rearm_pending = False
                self.rearms += 1
                logger.warning(
                    "re-arming the primary solver (stranded solve drained)"
                )

            def dispatch():
                if chaos.ACTIVE:
                    chaos.fire("solve")
                return self.model.solve_async(**kwargs)

            try:
                inner = self._run_deadlined(dispatch)
                self._last_ran = self.model
                return _WatchdogHandle(self, inner, kwargs)
            except SolveTimeout as e:
                self.timeouts += 1
                self._degrade(e)
            except Exception as e:  # noqa: BLE001 - the guard IS the point
                self._raise_if_paranoid(e)
                self.failures += 1
                self._degrade(e)
        # not armed / no async support / dispatch failed: solve NOW on
        # whatever solve() would have used and box the counts
        return _ReadyHandle(self.solve(**kwargs))

    def _run_fallback(self, kwargs) -> np.ndarray:
        self.last_solve_degraded = True
        fb_kwargs = dict(kwargs)
        # the greedy fallback cannot express the MILP's joint
        # min-utilization floor. On a degraded tick, floored workers WAIT
        # (their rows are zeroed so they receive nothing) rather than take
        # work below their floor — the documented degraded-mode semantics
        # (docs/scheduler.md "Solver watchdog and degraded mode")
        cpu_floor = fb_kwargs.pop("cpu_floor", None)
        if cpu_floor is not None:
            floored = np.asarray(cpu_floor) > 0
            if floored.any():
                free = np.array(fb_kwargs["free"], copy=True)
                free[floored] = 0
                nt_free = np.array(fb_kwargs["nt_free"], copy=True)
                nt_free[floored] = 0
                fb_kwargs["free"] = free
                fb_kwargs["nt_free"] = nt_free
        try:
            result = self.fallback.solve(**fb_kwargs)
        except Exception:  # noqa: BLE001 - never kill the scheduling loop
            self.skipped_ticks += 1
            self.last_solve_skipped = True
            logger.critical(
                "fallback solve failed too; assigning nothing this tick",
                exc_info=True,
            )
            n_b, n_v, _ = kwargs["needs"].shape
            self._last_ran = self.fallback
            return np.zeros((n_b, n_v, kwargs["free"].shape[0]),
                            dtype=np.int32)
        self.degraded_ticks += 1
        if self._bench_remaining > 0:
            self._bench_remaining -= 1
            if self._bench_remaining == 0:
                if self._abandoned_busy():
                    self._rearm_pending = True
                    logger.warning(
                        "bench window elapsed but a timed-out solve still "
                        "runs; staying on the fallback until it drains"
                    )
                else:
                    self.rearms += 1
                    logger.warning(
                        "re-arming the primary solver after %d clean "
                        "fallback ticks", self.rearm_ticks,
                    )
        self._last_ran = self.fallback
        return result


class _ReadyHandle:
    """Async-solve handle whose counts are already materialized."""

    __slots__ = ("_counts",)

    def __init__(self, counts):
        self._counts = counts

    def result(self):
        return self._counts


class _WatchdogHandle:
    """Deadline + exception guard around a primary model's pending solve.

    `result()` materializes the inner handle on the watchdog thread with
    the solve deadline; a timeout or exception degrades the watchdog
    (bench + resident-state invalidation, exactly like a synchronous
    failure) and re-solves the SAME dispatched snapshot on the host
    fallback — the captured kwargs are the assemble output of that tick,
    which stays untouched until the pipeline maps this handle."""

    __slots__ = ("_wd", "_inner", "_kwargs")

    def __init__(self, wd: "SolverWatchdog", inner, kwargs):
        self._wd = wd
        self._inner = inner
        self._kwargs = kwargs

    def result(self):
        wd = self._wd
        inner = self._inner
        try:
            out = wd._run_deadlined(inner.result)
            wd._last_ran = wd.model
            return out
        except SolveTimeout as e:
            wd.timeouts += 1
            wd._degrade(e)
        except Exception as e:  # noqa: BLE001 - the guard IS the point
            wd._raise_if_paranoid(e)
            wd.failures += 1
            wd._degrade(e)
        return wd._run_fallback(self._kwargs)
