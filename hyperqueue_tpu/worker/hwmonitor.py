"""Hardware telemetry for worker overviews.

Reference: crates/tako/src/internal/worker/hwmonitor/{mod,nvidia,amd}.rs —
CPU/memory/network usage plus GPU stats feeding WorkerOverview messages on a
configurable interval. Implemented over /proc and /sys (no extra deps); TPU
utilization is exposed when the accel sysfs paths exist.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import time
from hyperqueue_tpu.utils import clock


def parse_nvidia_smi_csv(text: str) -> list[dict]:
    """Parse `nvidia-smi --format=csv,noheader --query-gpu=pci.bus_id,
    utilization.gpu,memory.used,memory.total` output (reference
    hwmonitor/nvidia.rs parse_nvidia_gpu_stats)."""
    gpus = []
    for line in text.splitlines():
        parts = [p.strip() for p in line.split(",")]
        if len(parts) < 4 or not parts[0]:
            continue

        def num(value):
            digits = "".join(
                c for c in value if c.isdigit() or c == "."
            )
            try:
                return float(digits)
            except ValueError:
                return 0.0

        mem_used, mem_total = num(parts[2]), num(parts[3])
        gpus.append(
            {
                "id": parts[0],
                "vendor": "nvidia",
                "usage_percent": num(parts[1]),
                "mem_usage_percent": (
                    round(mem_used / mem_total * 100.0, 1)
                    if mem_total > 0
                    else 0.0
                ),
            }
        )
    return gpus


def parse_rocm_smi_json(text: str) -> list[dict]:
    """Parse `rocm-smi --json --showuse --showbus --showmemuse` output
    (reference hwmonitor/amd.rs parse_amd_gpu_stats)."""
    try:
        data = json.loads(text)
    except ValueError:
        return []
    gpus = []
    for card in sorted(data):
        stats = data[card]
        if not isinstance(stats, dict):
            continue

        def num(value):
            try:
                return float(value)
            except (TypeError, ValueError):
                return 0.0

        gpus.append(
            {
                "id": stats.get("PCI Bus", card),
                "vendor": "amd",
                "usage_percent": num(stats.get("GPU use (%)")),
                "mem_usage_percent": num(stats.get("GPU memory use (%)")),
            }
        )
    return gpus


class GpuMonitor:
    """NVIDIA (nvidia-smi) + AMD (rocm-smi) utilization collectors feeding
    worker overviews; vendors whose tool is absent are silently skipped
    (reference hwmonitor/{nvidia,amd}.rs)."""

    def __init__(self):
        self._nvidia = shutil.which("nvidia-smi")
        self._rocm = shutil.which("rocm-smi")

    @property
    def available(self) -> bool:
        return bool(self._nvidia or self._rocm)

    def sample(self) -> list[dict]:
        gpus: list[dict] = []
        if self._nvidia:
            try:
                out = subprocess.run(
                    [
                        self._nvidia,
                        "--format=csv,noheader",
                        "--query-gpu=pci.bus_id,utilization.gpu,"
                        "memory.used,memory.total",
                    ],
                    capture_output=True,
                    text=True,
                    timeout=5,
                    check=True,
                )
                gpus.extend(parse_nvidia_smi_csv(out.stdout))
            except (OSError, subprocess.SubprocessError):
                pass
        if self._rocm:
            try:
                out = subprocess.run(
                    [self._rocm, "--json", "--showuse", "--showbus",
                     "--showmemuse"],
                    capture_output=True,
                    text=True,
                    timeout=5,
                    check=True,
                )
                gpus.extend(parse_rocm_smi_json(out.stdout))
            except (OSError, subprocess.SubprocessError):
                pass
        return gpus


class HwSampler:
    def __init__(self):
        self._last_cpu = self._read_cpu_times()
        self._last_per_cpu = self._read_per_cpu_times()
        self._last_time = clock.monotonic()
        self._gpu = GpuMonitor()

    @staticmethod
    def _read_cpu_times():
        try:
            with open("/proc/stat") as f:
                fields = f.readline().split()[1:]
            numbers = [int(x) for x in fields]
            idle = numbers[3] + (numbers[4] if len(numbers) > 4 else 0)
            return sum(numbers), idle
        except (OSError, ValueError, IndexError):
            return (0, 0)

    @staticmethod
    def _read_per_cpu_times():
        """[(total, idle)] per logical cpu (reference cpu_util_table.rs
        shows a per-CPU utilization grid in the worker detail screen)."""
        out = []
        try:
            with open("/proc/stat") as f:
                for line in f:
                    if not line.startswith("cpu") or line.startswith("cpu "):
                        continue
                    numbers = [int(x) for x in line.split()[1:]]
                    idle = numbers[3] + (numbers[4] if len(numbers) > 4 else 0)
                    out.append((sum(numbers), idle))
        except (OSError, ValueError, IndexError):
            pass
        return out

    def sample(self) -> dict:
        total, idle = self._read_cpu_times()
        last_total, last_idle = self._last_cpu
        dt_total = total - last_total
        dt_idle = idle - last_idle
        self._last_cpu = (total, idle)
        cpu_usage = 0.0
        if dt_total > 0:
            cpu_usage = 100.0 * (1.0 - dt_idle / dt_total)

        per_cpu = self._read_per_cpu_times()
        per_core = []
        for i, (t, ii) in enumerate(per_cpu):
            if i < len(self._last_per_cpu):
                lt, li = self._last_per_cpu[i]
                dt, di = t - lt, ii - li
                per_core.append(
                    round(100.0 * (1.0 - di / dt), 1) if dt > 0 else 0.0
                )
        self._last_per_cpu = per_cpu

        mem_total = mem_avail = 0
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal:"):
                        mem_total = int(line.split()[1]) * 1024
                    elif line.startswith("MemAvailable:"):
                        mem_avail = int(line.split()[1]) * 1024
        except OSError:
            pass

        load = os.getloadavg() if hasattr(os, "getloadavg") else (0, 0, 0)
        out = {
            "timestamp": clock.now(),
            "cpu_usage_percent": round(cpu_usage, 1),
            "cpu_per_core_percent": per_core,
            "mem_total_bytes": mem_total,
            "mem_available_bytes": mem_avail,
            "loadavg_1m": load[0],
        }
        if self._gpu.available:
            out["gpus"] = self._gpu.sample()
        return out
