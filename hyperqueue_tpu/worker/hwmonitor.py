"""Hardware telemetry for worker overviews.

Reference: crates/tako/src/internal/worker/hwmonitor/{mod,nvidia,amd}.rs —
CPU/memory/network usage plus GPU stats feeding WorkerOverview messages on a
configurable interval. Implemented over /proc and /sys (no extra deps); TPU
utilization is exposed when the accel sysfs paths exist.
"""

from __future__ import annotations

import os
import time


class HwSampler:
    def __init__(self):
        self._last_cpu = self._read_cpu_times()
        self._last_per_cpu = self._read_per_cpu_times()
        self._last_time = time.monotonic()

    @staticmethod
    def _read_cpu_times():
        try:
            with open("/proc/stat") as f:
                fields = f.readline().split()[1:]
            numbers = [int(x) for x in fields]
            idle = numbers[3] + (numbers[4] if len(numbers) > 4 else 0)
            return sum(numbers), idle
        except (OSError, ValueError, IndexError):
            return (0, 0)

    @staticmethod
    def _read_per_cpu_times():
        """[(total, idle)] per logical cpu (reference cpu_util_table.rs
        shows a per-CPU utilization grid in the worker detail screen)."""
        out = []
        try:
            with open("/proc/stat") as f:
                for line in f:
                    if not line.startswith("cpu") or line.startswith("cpu "):
                        continue
                    numbers = [int(x) for x in line.split()[1:]]
                    idle = numbers[3] + (numbers[4] if len(numbers) > 4 else 0)
                    out.append((sum(numbers), idle))
        except (OSError, ValueError, IndexError):
            pass
        return out

    def sample(self) -> dict:
        total, idle = self._read_cpu_times()
        last_total, last_idle = self._last_cpu
        dt_total = total - last_total
        dt_idle = idle - last_idle
        self._last_cpu = (total, idle)
        cpu_usage = 0.0
        if dt_total > 0:
            cpu_usage = 100.0 * (1.0 - dt_idle / dt_total)

        per_cpu = self._read_per_cpu_times()
        per_core = []
        for i, (t, ii) in enumerate(per_cpu):
            if i < len(self._last_per_cpu):
                lt, li = self._last_per_cpu[i]
                dt, di = t - lt, ii - li
                per_core.append(
                    round(100.0 * (1.0 - di / dt), 1) if dt > 0 else 0.0
                )
        self._last_per_cpu = per_cpu

        mem_total = mem_avail = 0
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal:"):
                        mem_total = int(line.split()[1]) * 1024
                    elif line.startswith("MemAvailable:"):
                        mem_avail = int(line.split()[1]) * 1024
        except OSError:
            pass

        load = os.getloadavg() if hasattr(os, "getloadavg") else (0, 0, 0)
        return {
            "timestamp": time.time(),
            "cpu_usage_percent": round(cpu_usage, 1),
            "cpu_per_core_percent": per_core,
            "mem_total_bytes": mem_total,
            "mem_available_bytes": mem_avail,
            "loadavg_1m": load[0],
        }
