"""Task launcher: turn a compute message into a supervised OS process.

Reference: crates/hyperqueue/src/worker/start/program.rs (build_program_task)
— placeholder resolution, stdout/stderr redirection, stdin injection, HQ_*
environment, per-resource env vars with the concrete claimed indices, node
files for multi-node gangs, and a zero-cost mode for overhead benchmarking
(program.rs:498,622 `zero_worker`).

Two launch paths share the semantics above:

- `launch_task` — the original in-loop asyncio path, still used for
  stream-mode tasks (output pumps need the pipes in the worker), stdin
  injection, multi-node gangs, and as the fallback when the runner pool is
  unavailable.
- `LaunchPlan` — the amortized hot path. Tasks with identical (program,
  env template, stdio shape) share one plan: the merged environment,
  placeholder-free path prefixes, and directory creation are computed once
  per plan instead of once per task, and `instantiate` emits the small
  per-task spec a warm runner process (worker/runner.py) turns into a
  `posix_spawn`.
"""

from __future__ import annotations

import asyncio
import os
import shutil
import signal
from dataclasses import dataclass
from pathlib import Path

from hyperqueue_tpu.ids import task_id_job, task_id_task
from hyperqueue_tpu.utils.placeholders import fill_placeholders, task_placeholder_map
from hyperqueue_tpu.worker.allocator import Allocation
from hyperqueue_tpu.utils import clock


def stderr_tail(stderr_path: str | None, nbytes: int = 2048) -> str:
    """Last bytes of a task's stderr, the failure detail shown to the user.

    worker/runner.py mirrors this inline (its `-S` boot cannot import
    hyperqueue_tpu); keep the two in sync.
    """
    if not stderr_path:
        return ""
    try:
        with open(stderr_path, "rb") as f:
            f.seek(max(0, os.path.getsize(stderr_path) - nbytes))
            return f.read().decode(errors="replace")
    except OSError:
        return ""


def cleanup_task_files(
    code: int, rm_if_finished: tuple, cleanup_dirs: tuple
) -> None:
    if code == 0:
        # reference FileOnCloseBehavior::RmIfFinished (program.rs)
        for path in rm_if_finished:
            try:
                os.unlink(path)
            except OSError:
                pass
    # task dirs are transient scratch space, deleted when the task
    # completes whatever the outcome (reference program.rs task-dir
    # removal; tests/test_task_cleanup.py)
    for d in cleanup_dirs:
        shutil.rmtree(d, ignore_errors=True)


@dataclass
class LaunchedTask:
    process: asyncio.subprocess.Process | None
    stdout_path: str | None
    stderr_path: str | None
    pumps: tuple = ()  # stream-mode output pump tasks
    rm_if_finished: tuple = ()  # stdio paths removed on successful exit
    cleanup_dirs: tuple = ()  # task dirs removed once the task completes
    # wall clock of the actual process spawn, for the task's distributed
    # trace (worker/spawn span); 0.0 when unknown (zero-worker mode)
    spawned_wall: float = 0.0

    async def started(self) -> int:
        """Parity with PooledProcess.started(): the in-loop path has
        already spawned by the time the handle exists."""
        return self.process.pid if self.process is not None else 0

    async def wait(self) -> tuple[int, str]:
        """Returns (exit_code, error_detail)."""
        if self.process is None:  # zero-worker mode
            return 0, ""
        if self.pumps:
            await asyncio.gather(*self.pumps, return_exceptions=True)
        code = await self.process.wait()
        detail = stderr_tail(self.stderr_path) if code != 0 else ""
        cleanup_task_files(code, self.rm_if_finished, self.cleanup_dirs)
        return code, detail

    def kill(self) -> None:
        if self.process is not None and self.process.returncode is None:
            try:
                os.killpg(self.process.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError, OSError):
                try:
                    self.process.kill()
                except ProcessLookupError:
                    pass


async def launch_task(
    task_msg: dict,
    allocation: Allocation | None,
    server_uid: str,
    worker_id: int,
    zero_worker: bool = False,
    streamer=None,  # events.outputlog.StreamWriter when body["stream"] set
    extra_env: dict | None = None,
) -> LaunchedTask:
    """Spawn the task process described by a compute message.

    task_msg: {id, instance, body{cmd,env,cwd,stdout,stderr,stdin}, entries,
    node_ids?, node_hostnames?}.
    """
    if zero_worker:
        # benchmarking mode: skip process spawn entirely, instant success
        return LaunchedTask(process=None, stdout_path=None, stderr_path=None)

    body = task_msg.get("body") or {}
    task_id = task_msg["id"]
    job_id = task_id_job(task_id)
    job_task_id = task_id_task(task_id)
    submit_dir = body.get("submit_dir") or os.getcwd()
    mapping = task_placeholder_map(
        job_id=job_id,
        job_task_id=job_task_id,
        instance_id=task_msg.get("instance", 0),
        submit_dir=submit_dir,
        server_uid=server_uid,
    )

    cwd = body.get("cwd") or submit_dir
    cwd = fill_placeholders(cwd, mapping)
    mapping["CWD"] = cwd
    Path(cwd).mkdir(parents=True, exist_ok=True)

    env = dict(os.environ)
    env.update({k: str(v) for k, v in (body.get("env") or {}).items()})
    env.update(extra_env or {})
    env["HQ_JOB_ID"] = str(job_id)
    env["HQ_TASK_ID"] = str(job_task_id)
    env["HQ_INSTANCE_ID"] = str(task_msg.get("instance", 0))
    env["HQ_SUBMIT_DIR"] = submit_dir
    env["HQ_SERVER_UID"] = server_uid
    env["HQ_WORKER_ID"] = str(worker_id)
    env["HQ_ENTRY"] = task_msg.get("entry") or body.get("entry", "") or ""
    if not env["HQ_ENTRY"]:
        env.pop("HQ_ENTRY")

    if allocation is not None:
        for claim in allocation.claims:
            name = claim.resource
            value = claim.env_value()
            env[f"HQ_RESOURCE_VALUES_{name}"] = value
            env[f"HQ_RESOURCE_REQUEST_{name}"] = str(claim.amount())
            if name == "cpus":
                env["HQ_CPUS"] = value
                # CPU pinning hint for OpenMP-style programs (reference
                # program.rs:350 additionally taskset-pins; we export the
                # portable subset). A user-supplied --env OMP_NUM_THREADS
                # wins (reference test_do_not_override_set_omp_num_threads)
                if "OMP_NUM_THREADS" not in (body.get("env") or {}):
                    env["OMP_NUM_THREADS"] = str(
                        max(len(claim.indices), 1)
                    )

    cleanup_dirs: list[str] = []

    # optional private task directory (reference program.rs task-dir)
    if body.get("task_dir"):
        task_dir = Path(cwd) / f".hq-task-dir-{job_id}-{job_task_id}-{task_msg.get('instance', 0)}"
        task_dir.mkdir(parents=True, exist_ok=True)
        env["HQ_TASK_DIR"] = str(task_dir)
        env.setdefault("TMPDIR", str(task_dir))
        cleanup_dirs.append(str(task_dir))

    # multi-node gang: write the node file and expose it
    node_hostnames = task_msg.get("node_hostnames")
    if node_hostnames:
        # instance-suffixed like the private task dir: on a shared FS a dying
        # prior instance's cleanup must not delete the rescheduled
        # instance's node file
        task_dir = (
            Path(cwd)
            / f".hq-task-{job_id}-{job_task_id}-{task_msg.get('instance', 0)}"
        )
        task_dir.mkdir(parents=True, exist_ok=True)
        node_file = task_dir / "hq_nodes"
        node_file.write_text("\n".join(node_hostnames) + "\n")
        cleanup_dirs.append(str(task_dir))
        env["HQ_NODE_FILE"] = str(node_file)
        env["HQ_HOST_FILE"] = str(node_file)
        env["HQ_NUM_NODES"] = str(len(node_hostnames))

    stream_mode = streamer is not None and body.get("stream")

    rm_paths: list[str] = []

    def open_stdio(key: str):
        if stream_mode:
            return asyncio.subprocess.PIPE, None
        spec = body.get(key)
        if spec == "none":
            return asyncio.subprocess.DEVNULL, None
        # `<path>:rm-if-finished` / `:rm-if-finished` (default path): remove
        # the file when the task exits successfully (reference StdioDefInput)
        rm_on_ok = False
        if spec and spec.endswith(":rm-if-finished"):
            rm_on_ok = True
            spec = spec[: -len(":rm-if-finished")]
        if not spec:
            spec = f"%{{SUBMIT_DIR}}/job-%{{JOB_ID}}/%{{TASK_ID}}.{key}"
        path = fill_placeholders(spec, mapping)
        Path(path).parent.mkdir(parents=True, exist_ok=True)
        if rm_on_ok:
            rm_paths.append(path)
        return open(path, "wb"), path

    stdout_f, stdout_path = open_stdio("stdout")
    stderr_f, stderr_path = open_stdio("stderr")

    stdin_data = body.get("stdin")
    cmd = [fill_placeholders(str(c), mapping) for c in body["cmd"]]
    # CPU pinning (reference program.rs:350): taskset with the claimed cpu
    # indices, or OMP env pinning
    pin_mode = body.get("pin")
    if pin_mode and allocation is not None:
        cpu_claim = allocation.claim_for("cpus")
        if cpu_claim is not None and cpu_claim.indices:
            cpu_list = ",".join(cpu_claim.indices)
            env["HQ_PIN"] = pin_mode  # reference program.rs sets HQ_PIN
            if pin_mode == "taskset":
                cmd = ["taskset", "-c", cpu_list, *cmd]
            elif pin_mode == "omp":
                env["OMP_PLACES"] = "{" + "},{".join(cpu_claim.indices) + "}"
                env["OMP_PROC_BIND"] = "close"
    try:
        process = await asyncio.create_subprocess_exec(
            *cmd,
            cwd=cwd,
            env=env,
            stdin=asyncio.subprocess.PIPE if stdin_data else asyncio.subprocess.DEVNULL,
            stdout=stdout_f,
            stderr=stderr_f,
            start_new_session=True,  # own process group => killable subtree
        )
    finally:
        for f in (stdout_f, stderr_f):
            if hasattr(f, "close"):
                f.close()
    if stdin_data:
        process.stdin.write(stdin_data)
        process.stdin.write_eof()

    pumps = ()
    if stream_mode:
        from hyperqueue_tpu.events.outputlog import STDERR, STDOUT

        instance = task_msg.get("instance", 0)

        async def pump(reader, channel):
            while True:
                chunk = await reader.read(64 * 1024)
                if not chunk:
                    break
                streamer.write_chunk(task_id, instance, channel, chunk)

        pumps = (
            asyncio.create_task(pump(process.stdout, STDOUT)),
            asyncio.create_task(pump(process.stderr, STDERR)),
        )
    return LaunchedTask(
        process=process,
        stdout_path=stdout_path,
        stderr_path=stderr_path,
        pumps=pumps,
        rm_if_finished=tuple(rm_paths),
        cleanup_dirs=tuple(cleanup_dirs),
        spawned_wall=clock.now(),
    )


# ---------------------------------------------------------------------------
# Amortized launch plans for the warm runner pool
# ---------------------------------------------------------------------------

_DEFAULT_STDIO = "%{SUBMIT_DIR}/job-%{JOB_ID}/%{TASK_ID}.{key}"


def _absolute(path: str) -> str:
    """Resolve a relative spec against the worker's cwd — matching the
    in-loop path above. Runners chdir per task (posix_spawn has no cwd
    parameter), so every path shipped to one must be absolute or it would
    resolve against whatever directory the previous task left behind."""
    return path if os.path.isabs(path) else os.path.abspath(path)


def poolable(task_msg: dict) -> bool:
    """Can this compute message go through the runner pool?

    Stream mode needs the output pipes in the worker process (pump tasks),
    stdin injection needs a writable pipe, and multi-node gangs write node
    files with gang-level context — all three stay on the in-loop
    `launch_task` path.
    """
    body = task_msg.get("body") or {}
    return not (
        body.get("stream")
        or body.get("stdin")
        or task_msg.get("node_hostnames")
    )


class LaunchPlan:
    """Per-(program, env template, stdio shape) launch setup, built once.

    The plan owns everything identical across an array's tasks: the merged
    base environment (os.environ + submit env + job-level HQ_* vars), the
    filled-or-template cwd and stdio specs, and a memo of directories
    already created. `instantiate` does only the per-task work: task-id
    placeholder fills (skipped entirely for placeholder-free templates),
    claimed-resource env vars, and stdio paths.
    """

    _id_counter = 0

    __slots__ = (
        "plan_id", "body", "job_id", "submit_dir", "base_env", "base_mapping",
        "cmd", "cmd_has_ph", "cwd_spec", "cwd_has_ph", "cwd_static",
        "stdout_spec", "stdout_rm", "stderr_spec", "stderr_rm",
        "pin_mode", "task_dir", "omp_default", "tmpdir_default",
        "_made_dirs",
    )

    def __init__(
        self,
        task_msg: dict,
        server_uid: str,
        worker_id: int,
        static_env: dict | None = None,
    ):
        LaunchPlan._id_counter += 1
        self.plan_id = LaunchPlan._id_counter
        body = task_msg.get("body") or {}
        # the body dict is SHARED between an array's tasks (wire
        # shared/separate split); holding it keeps id(body) — the cache
        # key component — stable for the plan's lifetime
        self.body = body
        task_id = task_msg["id"]
        self.job_id = task_id_job(task_id)
        self.submit_dir = body.get("submit_dir") or os.getcwd()
        self.base_mapping = {
            "JOB_ID": str(self.job_id),
            "SUBMIT_DIR": self.submit_dir,
            "SERVER_UID": server_uid,
        }

        env = dict(os.environ)
        body_env = body.get("env") or {}
        env.update({k: str(v) for k, v in body_env.items()})
        env.update(static_env or {})
        env["HQ_JOB_ID"] = str(self.job_id)
        env["HQ_SUBMIT_DIR"] = self.submit_dir
        env["HQ_SERVER_UID"] = server_uid
        env["HQ_WORKER_ID"] = str(worker_id)
        self.base_env = env
        # a user-supplied OMP_NUM_THREADS wins over the per-claim default
        self.omp_default = "OMP_NUM_THREADS" not in body_env
        self.tmpdir_default = "TMPDIR" not in env

        self.cmd = [str(c) for c in body["cmd"]]
        self.cmd_has_ph = any("%{" in c for c in self.cmd)
        self.cwd_spec = body.get("cwd") or self.submit_dir
        self.cwd_has_ph = "%{" in self.cwd_spec
        self._made_dirs: set[str] = set()
        if not self.cwd_has_ph:
            self.cwd_static = _absolute(fill_placeholders(
                self.cwd_spec, self.base_mapping
            ))
            self._mkdir(self.cwd_static)
        else:
            self.cwd_static = None
        self.stdout_spec, self.stdout_rm = self._stdio_spec(body, "stdout")
        self.stderr_spec, self.stderr_rm = self._stdio_spec(body, "stderr")
        self.pin_mode = body.get("pin")
        self.task_dir = bool(body.get("task_dir"))

    @staticmethod
    def _stdio_spec(body: dict, key: str) -> tuple[str | None, bool]:
        """Returns (path template | None for devnull, rm-if-finished)."""
        spec = body.get(key)
        if spec == "none":
            return None, False
        rm_on_ok = False
        if spec and spec.endswith(":rm-if-finished"):
            rm_on_ok = True
            spec = spec[: -len(":rm-if-finished")]
        if not spec:
            spec = _DEFAULT_STDIO.replace("{key}", key)
        return spec, rm_on_ok

    def _mkdir(self, path: str) -> None:
        if path not in self._made_dirs:
            Path(path).mkdir(parents=True, exist_ok=True)
            self._made_dirs.add(path)

    def instantiate(
        self,
        task_msg: dict,
        allocation: Allocation | None,
        extra_env: dict | None = None,
    ) -> dict:
        """Per-task launch spec for the runner pool: cmd, env delta over the
        plan's base env, cwd, stdio paths, cleanup lists."""
        task_id = task_msg["id"]
        job_task_id = task_id_task(task_id)
        instance = task_msg.get("instance", 0)
        mapping = dict(self.base_mapping)
        mapping["TASK_ID"] = str(job_task_id)
        mapping["INSTANCE_ID"] = str(instance)
        if self.cwd_has_ph:
            cwd = _absolute(fill_placeholders(self.cwd_spec, mapping))
            self._mkdir(cwd)
        else:
            cwd = self.cwd_static
        mapping["CWD"] = cwd

        delta: dict[str, str] = {
            "HQ_TASK_ID": str(job_task_id),
            "HQ_INSTANCE_ID": str(instance),
        }
        if extra_env:
            delta.update(extra_env)
        entry = task_msg.get("entry") or self.body.get("entry", "") or ""
        if entry:
            delta["HQ_ENTRY"] = entry

        cmd = (
            [fill_placeholders(c, mapping) for c in self.cmd]
            if self.cmd_has_ph
            else self.cmd
        )
        if allocation is not None:
            for claim in allocation.claims:
                name = claim.resource
                value = claim.env_value()
                delta[f"HQ_RESOURCE_VALUES_{name}"] = value
                delta[f"HQ_RESOURCE_REQUEST_{name}"] = str(claim.amount())
                if name == "cpus":
                    delta["HQ_CPUS"] = value
                    if self.omp_default:
                        delta["OMP_NUM_THREADS"] = str(
                            max(len(claim.indices), 1)
                        )
            if self.pin_mode:
                cpu_claim = allocation.claim_for("cpus")
                if cpu_claim is not None and cpu_claim.indices:
                    delta["HQ_PIN"] = self.pin_mode
                    if self.pin_mode == "taskset":
                        cmd = [
                            "taskset", "-c", ",".join(cpu_claim.indices),
                            *cmd,
                        ]
                    elif self.pin_mode == "omp":
                        delta["OMP_PLACES"] = (
                            "{" + "},{".join(cpu_claim.indices) + "}"
                        )
                        delta["OMP_PROC_BIND"] = "close"

        cleanup_dirs: list[str] = []
        if self.task_dir:
            task_dir = (
                Path(cwd)
                / f".hq-task-dir-{self.job_id}-{job_task_id}-{instance}"
            )
            task_dir.mkdir(parents=True, exist_ok=True)
            delta["HQ_TASK_DIR"] = str(task_dir)
            if self.tmpdir_default:
                delta["TMPDIR"] = str(task_dir)
            cleanup_dirs.append(str(task_dir))

        rm_paths: list[str] = []

        def stdio_path(spec: str | None, rm: bool) -> str | None:
            if spec is None:
                return None
            path = fill_placeholders(spec, mapping) if "%{" in spec else spec
            path = _absolute(path)
            parent = os.path.dirname(path)
            if parent:
                self._mkdir(parent)
            if rm:
                rm_paths.append(path)
            return path

        return {
            "cmd": cmd,
            "env": delta,
            "cwd": cwd,
            "stdout": stdio_path(self.stdout_spec, self.stdout_rm),
            "stderr": stdio_path(self.stderr_spec, self.stderr_rm),
            "rm_if_finished": tuple(rm_paths),
            "cleanup_dirs": tuple(cleanup_dirs),
        }
