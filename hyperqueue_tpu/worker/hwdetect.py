"""Hardware detection for worker resource descriptors.

Reference: crates/hyperqueue/src/worker/hwdetect.rs:22-168 — CPUs with NUMA
groups from /sys, hyper-thread sibling pruning, GPUs from CUDA_VISIBLE_DEVICES
and /proc, memory from /proc/meminfo. Additionally (TPU-native): TPU chips
from /dev/accel* and TPU_VISIBLE_DEVICES.
"""

from __future__ import annotations

import glob
import os
import re
from pathlib import Path

from hyperqueue_tpu.resources.amount import FRACTIONS_PER_UNIT
from hyperqueue_tpu.resources.descriptor import (
    ResourceDescriptor,
    ResourceDescriptorItem,
)


def detect_cpus(no_hyper_threading: bool = False) -> ResourceDescriptorItem:
    """NUMA-grouped CPU list; falls back to a flat range."""
    node_dirs = sorted(
        glob.glob("/sys/devices/system/node/node[0-9]*"),
        key=lambda p: int(re.search(r"node(\d+)$", p).group(1)),
    )
    try:
        available = sorted(os.sched_getaffinity(0))
    except AttributeError:
        available = list(range(os.cpu_count() or 1))
    avail_set = set(available)

    pruned: set[int] = set()
    if no_hyper_threading:
        for cpu in available:
            sibling_file = Path(
                f"/sys/devices/system/cpu/cpu{cpu}/topology/thread_siblings_list"
            )
            if sibling_file.exists():
                siblings = _parse_cpu_list(sibling_file.read_text())
                for extra in siblings[1:]:
                    pruned.add(extra)
    usable = [c for c in available if c not in pruned]

    if len(node_dirs) > 1:
        groups: list[list[str]] = []
        seen: set[int] = set()
        for node_dir in node_dirs:
            cpulist = Path(node_dir) / "cpulist"
            if not cpulist.exists():
                continue
            cpus = [
                c
                for c in _parse_cpu_list(cpulist.read_text())
                if c in avail_set and c not in pruned and c not in seen
            ]
            seen.update(cpus)
            if cpus:
                groups.append([str(c) for c in cpus])
        if len(groups) > 1:
            return ResourceDescriptorItem.group_list("cpus", groups)
    return ResourceDescriptorItem.list("cpus", [str(c) for c in usable])


def _parse_cpu_list(text: str) -> list[int]:
    out: list[int] = []
    for part in text.strip().split(","):
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-")
            out.extend(range(int(lo), int(hi) + 1))
        else:
            out.append(int(part))
    return out


def detect_gpus() -> ResourceDescriptorItem | None:
    visible = os.environ.get("CUDA_VISIBLE_DEVICES") or os.environ.get(
        "HIP_VISIBLE_DEVICES"
    )
    if visible:
        ids = [v.strip() for v in visible.split(",") if v.strip()]
        if ids:
            return ResourceDescriptorItem.list("gpus", ids)
    nvidia = sorted(glob.glob("/proc/driver/nvidia/gpus/*"))
    if nvidia:
        return ResourceDescriptorItem.list(
            "gpus", [str(i) for i in range(len(nvidia))]
        )
    return None


def detect_tpus() -> ResourceDescriptorItem | None:
    visible = os.environ.get("TPU_VISIBLE_DEVICES")
    if visible:
        ids = [v.strip() for v in visible.split(",") if v.strip()]
        if ids:
            return ResourceDescriptorItem.list("tpus", ids)
    accels = sorted(glob.glob("/dev/accel[0-9]*")) + sorted(
        glob.glob("/dev/vfio/[0-9]*")
    )
    if accels:
        return ResourceDescriptorItem.list(
            "tpus", [str(i) for i in range(len(accels))]
        )
    return None


def detect_memory() -> ResourceDescriptorItem | None:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    kib = int(line.split()[1])
                    # expose memory in MiB units
                    mib = kib // 1024
                    return ResourceDescriptorItem.sum(
                        "mem", mib * FRACTIONS_PER_UNIT
                    )
    except OSError:
        pass
    return None


def detect_resources(
    n_cpus: int | None = None, no_hyper_threading: bool = False,
    with_memory: bool = False,
) -> ResourceDescriptor:
    items = []
    if n_cpus is not None:
        items.append(ResourceDescriptorItem.range("cpus", 0, n_cpus - 1))
    else:
        items.append(detect_cpus(no_hyper_threading=no_hyper_threading))
    for detector in (detect_gpus, detect_tpus):
        item = detector()
        if item is not None:
            items.append(item)
    if with_memory:
        mem = detect_memory()
        if mem is not None:
            items.append(mem)
    return ResourceDescriptor(items=tuple(items))
