"""Worker-side manager of warm runner processes (worker/runner.py).

The pool pre-forks N runners at worker start (N sized to the worker's CPU
capacity). Launching a task costs one small frame written to a runner's
stdin — the runner `posix_spawn`s the payload off the worker's event loop
and streams spawn/exit events back. Launch plans (worker/launcher.py) are
replicated to a runner lazily the first time a launch references them, so
a 10k-task array ships its environment once per runner, not once per task.

A runner that dies mid-task is detected by EOF on its stdout: every
in-flight task on it is failed (never hung) and the runner is respawned,
subject to a restart budget so a crash-looping runner degrades the pool
instead of fork-bombing the node.
"""

from __future__ import annotations

import asyncio
import logging
import os
import signal
import struct
import sys
import time

import msgpack

from hyperqueue_tpu.utils.metrics import REGISTRY
from hyperqueue_tpu.worker.launcher import cleanup_task_files
from hyperqueue_tpu.utils import clock

logger = logging.getLogger("hq.worker.pool")

_LEN = struct.Struct("<I")

_RUNNER_RESTARTS = REGISTRY.counter(
    "hq_worker_runner_restarts_total",
    "runner processes respawned after a crash",
)
_RUNNER_CRASH_FAILS = REGISTRY.counter(
    "hq_worker_runner_crash_failed_tasks_total",
    "in-flight tasks failed because their runner process died",
)


def _runner_argv_env() -> tuple[list[str], dict]:
    """Command line + environment for one runner process, tuned for boot
    speed: run runner.py by PATH under `-S` (skips site/.pth processing —
    ~0.15 s per interpreter on hosts with heavyweight site hooks) with
    PYTHONPATH pointing straight at msgpack's site-packages, the runner's
    only non-stdlib import. Falls back to a plain `-m` boot when either
    file location is unknowable (zipped/namespace installs)."""
    env = dict(os.environ)
    # the image's sitecustomize initializes jax (seconds + chip
    # contention) in any python process carrying the relay trigger;
    # runners never touch jax
    env.pop("PALLAS_AXON_POOL_IPS", None)
    from hyperqueue_tpu.worker import runner as _runner_mod

    runner_file = getattr(_runner_mod, "__file__", None)
    msgpack_file = getattr(msgpack, "__file__", None)
    if runner_file and msgpack_file:
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(msgpack_file))
        return [sys.executable, "-S", runner_file], env
    return [sys.executable, "-m", "hyperqueue_tpu.worker.runner"], env


class RunnerCrashed(Exception):
    """The runner supervising this task died before reporting its exit."""


class SpawnFailed(Exception):
    """The runner could not spawn the payload (bad program, cwd, perms)."""


class PooledProcess:
    """LaunchedTask-compatible handle for a payload supervised by a runner
    (worker/launcher.py LaunchedTask is the asyncio-path twin)."""

    __slots__ = (
        "_runner", "key", "pid", "spawned", "exited",
        "stdout_path", "stderr_path", "rm_if_finished", "cleanup_dirs",
        "spawned_wall",
    )

    def __init__(self, runner: "_Runner", key: int, spec: dict,
                 ack: bool = False):
        self._runner = runner
        self.key = key
        self.pid = 0
        # wall clock of the runner's spawn ack (trace worker/spawn span);
        # stays 0.0 on the no-ack hot path, where the in-order dispatch
        # itself is the spawn and the worker's own stamp stands in
        self.spawned_wall = 0.0
        loop = asyncio.get_running_loop()
        self.spawned: asyncio.Future | None = (
            loop.create_future() if ack else None
        )
        self.exited: asyncio.Future = loop.create_future()
        self.stdout_path = spec.get("stdout")
        self.stderr_path = spec.get("stderr")
        self.rm_if_finished = spec.get("rm_if_finished") or ()
        self.cleanup_dirs = spec.get("cleanup_dirs") or ()

    async def started(self) -> int:
        """With ack=True: resolves to the payload pid once the runner
        spawned it; raises on spawn failure (bad program, unreachable cwd,
        dead runner). Without the ack the dispatch itself is the start."""
        if self.spawned is None:
            return self.pid
        return await asyncio.shield(self.spawned)

    async def wait(self) -> tuple[int, str]:
        try:
            code, detail = await asyncio.shield(self.exited)
        except SpawnFailed:
            # the caller reports a launch failure, not a task exit; the
            # task dir was created at instantiate time and must not leak
            cleanup_task_files(-1, self.rm_if_finished, self.cleanup_dirs)
            raise
        except RunnerCrashed as e:
            # fail, never hang: the payload may or may not still run, but
            # its supervisor is gone — report and let the crash-counter
            # policy decide the task's fate. Scratch dirs go too (same
            # whatever-the-outcome contract as LaunchedTask.wait); an
            # unkillable orphan payload loses its TMPDIR, which is fine —
            # its incarnation is already failed and fenced out.
            cleanup_task_files(-1, self.rm_if_finished, self.cleanup_dirs)
            return -1, str(e)
        cleanup_task_files(code, self.rm_if_finished, self.cleanup_dirs)
        return code, detail

    def kill(self) -> None:
        self._runner.send_kill(self.key)


class _Runner:
    def __init__(self, pool: "RunnerPool", index: int):
        self.pool = pool
        self.index = index
        self.proc: asyncio.subprocess.Process | None = None
        self.known_plans: set[int] = set()
        self.inflight: dict[int, PooledProcess] = {}
        self._reader: asyncio.Task | None = None
        # True from the moment EOF is observed on stdout until the respawn
        # completes. proc.returncode alone is NOT a liveness signal here —
        # the child watcher may not have reaped yet while _on_runner_exit
        # awaits the restart, and dispatching into that window would
        # register a task the replacement process never learns about.
        self.dead = False

    async def start(self) -> None:
        argv, env = _runner_argv_env()
        self.proc = await asyncio.create_subprocess_exec(
            *argv,
            stdin=asyncio.subprocess.PIPE,
            stdout=asyncio.subprocess.PIPE,
            stderr=None,  # runner tracebacks land in the worker's log
            env=env,
        )
        self.known_plans = set()
        self.dead = False
        self._reader = asyncio.create_task(self._read_loop())

    def send(self, msg: dict) -> None:
        data = msgpack.packb(msg, use_bin_type=True)
        self.proc.stdin.write(_LEN.pack(len(data)) + data)

    def send_kill(self, key: int) -> None:
        if self.proc is None or self.proc.stdin.is_closing():
            return
        self.send({"op": "kill", "key": key})

    async def _read_loop(self) -> None:
        reader = self.proc.stdout
        try:
            while True:
                header = await reader.readexactly(_LEN.size)
                (length,) = _LEN.unpack(header)
                msg = msgpack.unpackb(
                    await reader.readexactly(length), raw=False
                )
                self._dispatch(msg)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            # flagged BEFORE failing the in-flight set and with no await in
            # between: a concurrent launch() can never register a task on
            # this runner after its tasks were failed
            self.dead = True
            self._fail_inflight()
            await self.pool._on_runner_exit(self)

    def _dispatch(self, msg: dict) -> None:
        op = msg.get("op")
        task = self.inflight.get(msg.get("key"))
        if task is None:
            return
        if op == "spawned":
            task.pid = msg.get("pid", 0)
            task.spawned_wall = clock.now()
            if task.spawned is not None and not task.spawned.done():
                task.spawned.set_result(task.pid)
        elif op == "spawn_error":
            self.inflight.pop(task.key, None)
            err = SpawnFailed(msg.get("error", "spawn failed"))
            if task.spawned is not None and not task.spawned.done():
                task.spawned.set_exception(err)
                task.spawned.exception()  # wait() may be the only awaiter
            if not task.exited.done():
                task.exited.set_exception(err)
                task.exited.exception()  # started() may be the only awaiter
        elif op == "exit":
            self.inflight.pop(task.key, None)
            if task.spawned is not None and not task.spawned.done():
                task.spawned.set_result(0)
            if not task.exited.done():
                task.exited.set_result(
                    (msg.get("code", -1), msg.get("detail", ""))
                )

    def _fail_inflight(self) -> None:
        if not self.inflight:
            return
        _RUNNER_CRASH_FAILS.inc(len(self.inflight))
        err = RunnerCrashed(
            "runner process died while supervising this task"
        )
        for task in self.inflight.values():
            if task.pid:
                # the dead supervisor can't reap its children: kill the
                # payloads whose pids we know (spawn-acked), so the failed
                # task's re-run never races a live orphan. Un-acked
                # payloads are unkillable from here — they run to their
                # natural exit as orphans.
                try:
                    os.killpg(task.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError, OSError):
                    try:
                        os.kill(task.pid, signal.SIGKILL)
                    except (ProcessLookupError, OSError):
                        pass
            if task.spawned is not None and not task.spawned.done():
                task.spawned.set_exception(err)
                task.spawned.exception()  # may go unawaited on teardown
            if not task.exited.done():
                task.exited.set_exception(err)
                task.exited.exception()
        self.inflight.clear()

    def close_stdin(self) -> None:
        if self.proc is not None and not self.proc.stdin.is_closing():
            try:
                self.proc.stdin.close()
            except (ConnectionError, OSError):
                pass


class RunnerPool:
    # POOL-WIDE crash budget: more than BUDGET runner deaths within WINDOW
    # seconds permanently disables the pool for this worker's lifetime
    # (launch() raises; the runtime falls back to the in-loop asyncio
    # spawn path). Deliberately conservative: the budget is the fork-bomb
    # guard, the fallback path is fully functional, and a node-wide event
    # that kills several runners at once is exactly when respawn-looping
    # python interpreters would make things worse.
    RESTART_BUDGET = 5
    RESTART_WINDOW = 60.0

    def __init__(self, size: int):
        self.size = max(1, size)
        self.runners: list[_Runner] = []
        self._key_counter = 0
        self._closing = False
        self._restarts: list[float] = []  # monotonic stamps of respawns
        self.broken = False

    async def start(self) -> None:
        """Spawn the runners concurrently; each joins the pool as soon as
        it is up (callers launch through whatever is ready — the runtime
        falls back to in-loop spawn while the pool warms for ~0.5 s)."""
        async def one(i: int) -> None:
            runner = _Runner(self, i)
            await runner.start()
            if self._closing:
                runner.close_stdin()
                return
            self.runners.append(runner)

        await asyncio.gather(
            *(one(i) for i in range(self.size)), return_exceptions=False
        )

    async def _on_runner_exit(self, runner: _Runner) -> None:
        if self._closing or self.broken:
            return
        now = clock.monotonic()
        self._restarts = [
            t for t in self._restarts if now - t < self.RESTART_WINDOW
        ]
        if len(self._restarts) >= self.RESTART_BUDGET:
            logger.error(
                "runner %d exceeded the restart budget (%d in %.0fs); "
                "disabling the pool — tasks fall back to in-loop spawn",
                runner.index, self.RESTART_BUDGET, self.RESTART_WINDOW,
            )
            self.broken = True
            return
        self._restarts.append(now)
        _RUNNER_RESTARTS.inc()
        logger.warning("runner %d died; respawning", runner.index)
        try:
            await runner.start()
        except OSError as e:
            logger.error("runner respawn failed (%s); disabling pool", e)
            self.broken = True

    @property
    def available(self) -> bool:
        return bool(self.runners) and not self.broken and not self._closing

    def ensure_plan(self, runner: _Runner, plan) -> None:
        if plan.plan_id not in runner.known_plans:
            runner.send(
                {"op": "plan", "plan": plan.plan_id, "env": plan.base_env}
            )
            runner.known_plans.add(plan.plan_id)

    async def launch(self, plan, spec: dict, ack: bool = False) -> PooledProcess:
        """Dispatch one payload to the least-loaded live runner. With
        `ack` the runner confirms the spawn (started() resolves to the
        real pid); without it the exit frame is the only per-task reply."""
        if not self.available:
            raise RunnerCrashed("runner pool is unavailable")
        runner = min(
            (
                r for r in self.runners
                if not r.dead and r.proc.returncode is None
            ),
            key=lambda r: len(r.inflight),
            default=None,
        )
        if runner is None:
            raise RunnerCrashed("no live runner")
        self.ensure_plan(runner, plan)
        self._key_counter += 1
        key = self._key_counter
        task = PooledProcess(runner, key, spec, ack=ack)
        runner.inflight[key] = task
        msg = {
            "op": "launch", "key": key, "plan": plan.plan_id,
            "cmd": spec["cmd"],
        }
        if ack:
            msg["ack"] = True
        for field in ("env", "cwd", "stdout", "stderr"):
            if spec.get(field) is not None:
                msg[field] = spec[field]
        runner.send(msg)
        try:
            stdin = runner.proc.stdin
            if stdin.transport.get_write_buffer_size() > 1 << 20:
                await stdin.drain()
        except asyncio.CancelledError:
            # the launch frame is already on its way: a cancellation here
            # (task canceled mid-dispatch) must not leak the payload
            runner.send_kill(key)
            raise
        return task

    async def close(self) -> None:
        """Drain: EOF every runner's stdin (each kills its children and
        exits), then reap with a deadline."""
        self._closing = True
        for runner in self.runners:
            runner.close_stdin()
        for runner in self.runners:
            if runner.proc is None:
                continue
            try:
                await asyncio.wait_for(runner.proc.wait(), timeout=5)
            except asyncio.TimeoutError:
                try:
                    runner.proc.kill()
                except ProcessLookupError:
                    pass
        self.runners = []
