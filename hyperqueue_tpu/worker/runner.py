"""Warm runner process: the worker's pre-forked spawn helper.

One runner is a long-lived child of the worker. It reads launch specs over
stdin (u32-LE length-prefixed msgpack frames), `posix_spawn`s the payload,
and reports spawn/exit events back over stdout. This removes the two
dominant per-task costs of the in-loop `asyncio.create_subprocess_exec`
path:

- **fork of the worker interpreter**: asyncio's subprocess machinery
  fork+execs the (large) worker process per task; the runner's
  `posix_spawn` is the vfork-style fast path and never copies the worker.
- **event-loop serialization**: spawn syscalls block whichever process
  issues them; in the runner they overlap with the worker's message loop,
  uplink batching, and the other runners.

Tasks with the same launch *plan* (program + env template + stdio shape,
see worker/launcher.py LaunchPlan) share the plan's prebuilt environment:
the worker sends the plan once per runner and each launch frame carries
only the per-task delta (task id vars, claimed resources, stdio paths).

Protocol (worker -> runner):
  {op: "plan", plan: id, env: {K: V}}           cache a base environment
  {op: "launch", key, plan?, cmd, env?, cwd?,
   stdout?, stderr?}                            spawn one payload
  {op: "kill", key}                             SIGKILL the payload's group
Runner -> worker:
  {op: "spawned", key, pid}
  {op: "spawn_error", key, error}
  {op: "exit", key, code, detail}

EOF on stdin (worker died or pool drain) kills every supervised child and
exits — a runner never outlives its worker.
"""

from __future__ import annotations

import os
import signal
import struct
import sys
import threading
import time

import msgpack

_LEN = struct.Struct("<I")


def _read_exact(fd: int, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = os.read(fd, n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class Runner:
    def __init__(self):
        self._plans: dict[int, dict] = {}
        self._lock = threading.Lock()  # children maps + stdout writes
        self._children: dict[int, tuple[int, str | None]] = {}  # pid ->
        self._key_pid: dict[int, int] = {}
        # pid -> wait status for children the reaper collected BEFORE
        # _spawn registered them (a payload like `true` can exit between
        # posix_spawn returning and the bookkeeping below); registration
        # reconciles so the exit frame is never lost
        self._unclaimed: dict[int, int] = {}
        # kills that arrived before (or instead of) their launch frame
        self._pending_kills: set[int] = set()
        self._have_child = threading.Condition(self._lock)
        self._closing = False
        self._devnull = os.open(os.devnull, os.O_RDWR)
        self._cwd = os.getcwd()
        # POSIX_SPAWN_SETSID may be unsupported; fall back to a fresh
        # process group (still killable as a subtree via killpg)
        self._setsid_ok = True

    def _send(self, obj: dict) -> None:
        data = msgpack.packb(obj, use_bin_type=True)
        try:
            with self._lock:
                os.write(1, _LEN.pack(len(data)) + data)
        except OSError:
            pass  # worker gone mid-shutdown; the exit is moot

    # --- spawn -----------------------------------------------------------
    def _spawn(self, msg: dict) -> None:
        key = msg["key"]
        with self._lock:
            if key in self._pending_kills:
                self._pending_kills.discard(key)
                canceled = True
            else:
                canceled = False
            # keys are monotonic and launches arrive in key order on this
            # stdin: a pending kill below the current key can never match a
            # future launch (its payload already exited before the kill) —
            # prune, or cancel-after-exit races grow the set forever
            if self._pending_kills:
                self._pending_kills = {
                    k for k in self._pending_kills if k > key
                }
        if canceled:
            self._send({"op": "exit", "key": key, "code": -9,
                        "detail": "killed before spawn"})
            return
        plan = self._plans.get(msg.get("plan", -1))
        env = dict(plan["env"]) if plan else {}
        delta = msg.get("env")
        if delta:
            env.update(delta)
        cmd = [str(c) for c in msg["cmd"]]
        cwd = msg.get("cwd")
        stdout_path = msg.get("stdout")
        stderr_path = msg.get("stderr")
        fds: list[int] = []
        try:
            if cwd and cwd != self._cwd:
                # posix_spawn has no cwd parameter; only this thread spawns,
                # so the runner-global cwd is safe to retarget per launch
                try:
                    os.chdir(cwd)
                except FileNotFoundError:
                    # the plan mkdirs cwd once; recreate if deleted mid-array
                    os.makedirs(cwd, exist_ok=True)
                    os.chdir(cwd)
                self._cwd = cwd
            actions = [(os.POSIX_SPAWN_DUP2, self._devnull, 0)]
            for path, target in ((stdout_path, 1), (stderr_path, 2)):
                if path is None:
                    actions.append((os.POSIX_SPAWN_DUP2, self._devnull, target))
                else:
                    fd = self._open_stdio(path)
                    fds.append(fd)
                    actions.append((os.POSIX_SPAWN_DUP2, fd, target))
            if self._setsid_ok:
                try:
                    pid = os.posix_spawnp(
                        cmd[0], cmd, env, file_actions=actions, setsid=True
                    )
                except NotImplementedError:
                    self._setsid_ok = False
                    pid = os.posix_spawnp(
                        cmd[0], cmd, env, file_actions=actions, setpgroup=0
                    )
            else:
                pid = os.posix_spawnp(
                    cmd[0], cmd, env, file_actions=actions, setpgroup=0
                )
        except Exception as e:  # noqa: BLE001 - report, keep the runner alive
            self._send({"op": "spawn_error", "key": key, "error": str(e)})
            return
        finally:
            for fd in fds:
                os.close(fd)
        with self._have_child:
            status = self._unclaimed.pop(pid, None)
            if status is None:
                self._children[pid] = (key, stderr_path)
                self._key_pid[key] = pid
                self._have_child.notify()
        if msg.get("ack"):
            # the spawn ack is opt-in: on the hot path the exit frame is
            # the only per-task uplink, halving runner->worker wakeups
            self._send({"op": "spawned", "key": key, "pid": pid})
        if status is not None:
            # the child already exited and was reaped unclaimed
            self._report_exit(key, stderr_path, status)

    @staticmethod
    def _open_stdio(path: str) -> int:
        flags = os.O_WRONLY | os.O_CREAT | os.O_TRUNC
        try:
            return os.open(path, flags, 0o644)
        except FileNotFoundError:
            # the worker's LaunchPlan mkdirs stdio parents once per plan;
            # recreate if an external cleanup removed the dir mid-array
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            return os.open(path, flags, 0o644)

    def _kill(self, key: int) -> None:
        with self._lock:
            pid = self._key_pid.get(key)
            if pid is None:
                # launch frame not processed yet (or already exited): mark
                # so a queued launch is refused instead of racing the kill
                self._pending_kills.add(key)
                return
        self._kill_pid(pid)

    @staticmethod
    def _kill_pid(pid: int) -> None:
        try:
            os.killpg(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                os.kill(pid, signal.SIGKILL)
            except (ProcessLookupError, OSError):
                pass

    # --- reap ------------------------------------------------------------
    def _reaper(self) -> None:
        while True:
            with self._have_child:
                while not self._children and not self._closing:
                    self._have_child.wait()
                if self._closing and not self._children:
                    return
            try:
                pid, status = os.waitpid(-1, 0)
            except ChildProcessError:
                # no children despite bookkeeping saying otherwise: yield
                # instead of hot-spinning while the maps catch up
                time.sleep(0.005)
                continue
            with self._lock:
                entry = self._children.pop(pid, None)
                if entry is not None:
                    self._key_pid.pop(entry[0], None)
                else:
                    # exited before _spawn registered it: park the status,
                    # registration sends the exit frame
                    self._unclaimed[pid] = status
            if entry is None:
                continue
            key, stderr_path = entry
            self._report_exit(key, stderr_path, status)

    def _report_exit(self, key: int, stderr_path: str | None,
                     status: int) -> None:
        code = os.waitstatus_to_exitcode(status)
        detail = ""
        if code != 0 and stderr_path:
            try:
                size = os.path.getsize(stderr_path)
                with open(stderr_path, "rb") as f:
                    f.seek(max(0, size - 2048))
                    detail = f.read().decode(errors="replace")
            except OSError:
                pass
        self._send({"op": "exit", "key": key, "code": code,
                    "detail": detail})

    # --- main loop -------------------------------------------------------
    def run(self) -> int:
        reaper = threading.Thread(target=self._reaper, daemon=True)
        reaper.start()
        while True:
            header = _read_exact(0, _LEN.size)
            if header is None:
                break
            (length,) = _LEN.unpack(header)
            payload = _read_exact(0, length)
            if payload is None:
                break
            msg = msgpack.unpackb(payload, raw=False)
            op = msg.get("op")
            if op == "launch":
                self._spawn(msg)
            elif op == "kill":
                self._kill(msg["key"])
            elif op == "plan":
                self._plans[msg["plan"]] = msg
            elif op == "drop_plan":
                self._plans.pop(msg["plan"], None)
        # worker gone / drain requested: no payload outlives the worker.
        # The reaper owns waitpid — just kill and let it drain the zombies.
        with self._have_child:
            self._closing = True
            pids = list(self._children)
            self._have_child.notify()
        for pid in pids:
            self._kill_pid(pid)
        reaper.join(timeout=10)
        return 0


def main() -> int:
    signal.signal(signal.SIGINT, signal.SIG_IGN)  # worker decides lifetime
    return Runner().run()


if __name__ == "__main__":
    sys.exit(main())
