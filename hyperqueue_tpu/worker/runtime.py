"""Worker runtime: connect, register, run tasks, report results.

Reference: crates/tako/src/internal/worker/rpc.rs (run_worker) — a select loop
over the server message stream, heartbeat timer, idle timeout and time limit;
plus worker/reactor.rs (compute_tasks -> try_start_task -> launch). Tasks that
cannot allocate resources right now (fractional packing races) sit in a
blocked queue retried after every release.

Fail-safe extensions beyond the reference:

- ``--on-server-lost reconnect``: a lost server connection no longer
  strands the worker — running tasks keep running while the worker retries
  the registration handshake with jittered exponential backoff, re-reading
  the access record each attempt (a restarted server publishes a new
  instance dir with fresh ports and keys). The register message carries
  the still-running (task, instance) set; the server reattaches what its
  journal restore held for exactly those incarnations and orders the rest
  killed (stale incarnations requeued elsewhere).
- Unacked task-state uplinks are never lost to a dead connection: a send
  failure parks the batch in a replay buffer that is re-sent after
  reconnect, and a bounded log of final task messages is replayed too
  (covering completions whose send "succeeded" into a dying socket).
  Replays are safe because every task message carries its instance id and
  the server applies each (task, instance) transition at most once.
- Duplicate compute messages (chaos: duplicated frames, or a replayed
  server queue) are dropped by a bounded (task, instance) dedup set.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from collections import OrderedDict
from pathlib import Path

from hyperqueue_tpu.ids import task_id_job
from hyperqueue_tpu.server.worker import WorkerConfiguration
from hyperqueue_tpu.transport.auth import (
    ROLE_SERVER,
    ROLE_WORKER,
    AuthError,
    Connection,
    do_authentication,
)
from hyperqueue_tpu.transport.framing import read_trace
from hyperqueue_tpu.utils import chaos
from hyperqueue_tpu.utils.metrics import REGISTRY
from hyperqueue_tpu.utils.retry import jittered_backoff
from hyperqueue_tpu.worker.allocator import ResourceAllocator
from hyperqueue_tpu.worker.launcher import (
    LaunchedTask,
    LaunchPlan,
    launch_task,
    poolable,
)
from hyperqueue_tpu.worker.runner_pool import RunnerCrashed, RunnerPool
from hyperqueue_tpu.utils import clock

logger = logging.getLogger("hq.worker")

# worker-side metrics plane (utils/metrics.py). Everything here lives in
# the hq_worker_* namespace: gauge/counter samples piggyback on overview
# messages and the server re-exports them cluster-wide under a `worker`
# label, so the namespace is the fan-out filter.
_SPAWN_SECONDS = REGISTRY.histogram(
    "hq_worker_task_spawn_seconds",
    "compute-message accept to launch latency (runner-pool dispatch on "
    "the hot path, full process spawn on the in-loop path)",
)
_TASKS_DONE = REGISTRY.counter(
    "hq_worker_tasks_done_total",
    "tasks completed on this worker by outcome",
    labels=("outcome",),
)
_RECONNECT_ATTEMPTS = REGISTRY.counter(
    "hq_worker_reconnect_attempts_total",
    "registration attempts while reconnecting to a lost server",
)
_RECONNECTS = REGISTRY.counter(
    "hq_worker_reconnects_total",
    "successful re-registrations after a lost server connection",
)
_REPLAYED = REGISTRY.counter(
    "hq_worker_replayed_messages_total",
    "uplink messages parked by a dead connection and re-sent after "
    "reconnect",
)
_RUNNING = REGISTRY.gauge(
    "hq_worker_running_tasks", "tasks currently executing"
)
_PARKED = REGISTRY.gauge(
    "hq_worker_blocked_tasks",
    "tasks parked waiting for local resources",
)
_SENDQ = REGISTRY.gauge(
    "hq_worker_sendq_depth", "uplink messages awaiting the send drainer"
)
_PLAN_LOOKUPS = REGISTRY.counter(
    "hq_worker_launch_plan_total",
    "launch-plan cache lookups on the runner-pool dispatch path",
    labels=("result",),
)
_UPLINK_BATCH = REGISTRY.histogram(
    "hq_worker_uplink_batch_size",
    "messages coalesced per uplink frame by the send drainer",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512),
)
_CPU = REGISTRY.gauge(
    "hq_worker_cpu_percent", "node CPU utilization (HwSampler)"
)
_MEM_TOTAL = REGISTRY.gauge(
    "hq_worker_mem_total_bytes", "node memory total (HwSampler)"
)
_MEM_AVAILABLE = REGISTRY.gauge(
    "hq_worker_mem_available_bytes", "node memory available (HwSampler)"
)
_LOAD = REGISTRY.gauge("hq_worker_loadavg_1m", "node 1-minute load average")
_GPU = REGISTRY.gauge(
    "hq_worker_gpu_percent", "per-GPU utilization (HwSampler)",
    labels=("gpu",),
)
_GPU_MEM = REGISTRY.gauge(
    "hq_worker_gpu_mem_percent", "per-GPU memory utilization (HwSampler)",
    labels=("gpu",),
)
_PLANE_SHARE = REGISTRY.gauge(
    "hq_worker_profile_plane_cpu_share",
    "CPU cores used by each worker plane over the sampling window "
    "(sampling profiler, ISSUE 19); piggybacks on overview messages so "
    "the server re-exports it fleet-wide under a worker label",
    labels=("plane",), max_series=16,
)


class RunningTask:
    __slots__ = ("msg", "allocation", "launched", "future")

    def __init__(self, msg, allocation, launched, future):
        self.msg = msg
        self.allocation = allocation
        self.launched: LaunchedTask = launched
        self.future: asyncio.Task = future


class WorkerRuntime:
    # reconnect backoff: jittered exponential from BASE, capped at CAP;
    # each handshake attempt gets its own deadline so a blackholed SYN or
    # a wedged server that accepts but never answers cannot stall the
    # retry loop past --reconnect-timeout
    RECONNECT_BACKOFF_BASE = 0.25
    RECONNECT_BACKOFF_CAP = 5.0
    RECONNECT_ATTEMPT_TIMEOUT = 10.0
    # bounded memory for the duplicate-compute guard and the replayed
    # final-message log
    RECENT_TASKS_MAX = 8192
    DONE_LOG_MAX = 4096

    def __init__(
        self,
        host: str,
        port: int,
        secret_key: bytes | None,
        configuration: WorkerConfiguration,
        zero_worker: bool = False,
        server_dir: Path | None = None,
        metrics_port: int | None = None,
        metrics_host: str = "0.0.0.0",
        profile_hz: float = 19.0,
    ):
        self.host = host
        self.port = port
        self.secret_key = secret_key
        self.configuration = configuration
        self.zero_worker = zero_worker
        # where to re-read the access record from on reconnect (a restarted
        # server has a new instance dir: new ports, new keys)
        self.server_dir = Path(server_dir) if server_dir else None
        self.allocator = ResourceAllocator(configuration.descriptor)
        self.worker_id = 0
        self.server_uid = ""
        self.running: dict[int, RunningTask] = {}
        # resource-signature -> list of blocked task messages (FIFO)
        self.blocked: dict[tuple, list[dict]] = {}
        self._n_blocked = 0
        self._streamers: dict[str, object] = {}  # stream dir -> StreamWriter
        # stream dir -> number of RUNNING tasks currently holding the
        # writer: eviction may only close zero-refcount writers (closing an
        # in-use one fails its task's next write_chunk/close_task)
        self._streamer_users: dict[str, int] = {}
        self.last_task_time = clock.monotonic()
        self.started_at = clock.monotonic()
        self._conn: Connection | None = None
        self._send_lock = asyncio.Lock()
        self._sendq: asyncio.Queue = asyncio.Queue()
        # uplinks that could not be handed to a live connection; re-sent
        # (ahead of fresh traffic) after the next successful reconnect
        self._replay: list[dict] = []
        # bounded log of final task messages already handed to a socket:
        # a send into a dying connection can "succeed" without the server
        # ever seeing it, so these replay too (the server drops duplicates
        # by (task, instance)). Keyed by (id, instance, op) so a replayed
        # message passing through the drainer again cannot duplicate its
        # entry; harvested and cleared on reconnect (replayed copies
        # re-enter when their re-send happens), so it only ever holds the
        # last session's finals.
        self._done_log: OrderedDict[tuple, dict] = OrderedDict()
        # (task_id, instance) -> None for every compute accepted: duplicate
        # deliveries (chaos dup, replayed server queues) must not run twice
        self._recent_tasks: OrderedDict[tuple[int, int], None] = OrderedDict()
        # incarnations the server ordered killed at reconnect: their exit
        # must NOT be reported — if the server re-issued the task at the
        # SAME instance (a start it never journaled), a task_failed from
        # the killed copy would pass the fence and fail the live one
        self._discarded: set[int] = set()
        self._stop = asyncio.Event()
        # /readyz input: True only while a registered session is live —
        # flipped off the moment the session winds down, BEFORE the
        # reconnect backoff starts, so a probe during the gap reports the
        # worker unready instead of racing the re-registration
        self._session_live = False
        # federation worker lending (ISSUE 11): a `redirect` message sets
        # the sibling shard dir to re-register with and fires this event;
        # the session winds down and run() registers fresh over there
        self._redirect = asyncio.Event()
        self._redirect_target: Path | None = None
        # warm runner pool (worker/runner_pool.py): None while disabled
        # (--runner-pool 0, zero-worker mode, or the restart budget blew);
        # plan cache: (job_id, id(body)) -> LaunchPlan, LRU-bounded. Plans
        # hold their body so the id() key stays stable while cached.
        self.runner_pool: RunnerPool | None = None
        self._pool_warmup: asyncio.Task | None = None
        self._plan_cache: OrderedDict[tuple, LaunchPlan] = OrderedDict()
        self._rng = random.Random()
        # server-forced overview cadence (None = use configuration)
        self._overview_override: float | None = None
        self._overview_wake = asyncio.Event()
        self.localcomm = None
        # Prometheus endpoint: None = off (recording still happens; gauges
        # also piggyback on overview messages), 0 = ephemeral. Bind
        # 127.0.0.1 via --metrics-host to keep the (unauthenticated)
        # endpoint off shared networks.
        self.requested_metrics_port = metrics_port
        self.metrics_host = metrics_host
        self.metrics_port: int | None = None
        self._metrics_server = None
        # sampling profiler (ISSUE 19): 0 disables; the "runtime" plane is
        # this asyncio thread (drainer + overview are tasks on it)
        self.profile_hz = float(profile_hz)
        self._profiler_started = False

    def _publish_plane_shares(self) -> None:
        from hyperqueue_tpu.utils import profiler

        if not profiler.PROFILER.running:
            return
        _PLANE_SHARE.clear()
        for plane, agg in profiler.PROFILER.plane_shares().items():
            _PLANE_SHARE.labels(plane).set(agg["cpu"])

    def _collect_metrics(self) -> None:
        """Scrape-time gauges from live runtime state (collect hook — no
        hot-path bookkeeping needed for queue depths)."""
        _RUNNING.set(len(self.running))
        _PARKED.set(self._n_blocked)
        _SENDQ.set(self._sendq.qsize())
        self._publish_plane_shares()

    async def _send(self, msg: dict) -> None:
        """Enqueue an uplink message; a drainer batches queued messages into
        one frame (one encryption + one syscall for a burst of task events —
        the per-task overhead win analogous to the reference's shared/
        separate compute-message split, messages/worker.rs:28-54)."""
        self._sendq.put_nowait(msg)

    async def _send_drainer(self) -> None:
        # zero-worker mode is a control-plane benchmark instrument: tasks
        # complete in microseconds and the coalescing nap (a latency-for-
        # syscalls trade sized against millisecond process spawns) would
        # dominate the very overhead being measured. Bursts still batch
        # naturally below.
        flush_delay = (
            0.0 if self.zero_worker
            else max(self.configuration.uplink_flush_secs, 0.0)
        )
        while True:
            msg = await self._sendq.get()
            batch = [msg]
            while len(batch) < 512:
                try:
                    batch.append(self._sendq.get_nowait())
                except asyncio.QueueEmpty:
                    break
            if flush_delay > 0 and len(batch) == 1:
                # a lone message with no companions queued: wait the
                # bounded coalescing window so completions landing within
                # it ride the same frame (one encryption + one syscall +
                # one server recv wakeup for the burst) — the uplink half
                # of the batched completion plane. A burst already in the
                # queue skips the nap: the batch has formed by itself.
                try:
                    await asyncio.sleep(flush_delay)
                except asyncio.CancelledError:
                    self._replay.extend(batch)  # never lose the popped msg
                    raise
                while len(batch) < 512:
                    try:
                        batch.append(self._sendq.get_nowait())
                    except asyncio.QueueEmpty:
                        break
            _UPLINK_BATCH.observe(len(batch))
            if chaos.ACTIVE:
                injected = []
                try:
                    for m in batch:
                        action = await chaos.on_message(
                            "worker.send", op=m.get("op")
                        )
                        if action == "drop":
                            continue
                        injected.append(m)
                        if action == "dup":
                            injected.append(m)
                except asyncio.CancelledError:
                    # teardown caught the drainer mid-injection: nothing was
                    # sent yet, park the whole popped batch
                    self._replay.extend(batch)
                    raise
                batch = injected
                if not batch:
                    continue
            for m in batch:
                if m.get("op") in ("task_finished", "task_failed"):
                    key = (m.get("id"), m.get("instance"), m.get("op"))
                    if key not in self._done_log:
                        self._done_log[key] = m
                        while len(self._done_log) > self.DONE_LOG_MAX:
                            self._done_log.popitem(last=False)
            try:
                async with self._send_lock:
                    if len(batch) == 1:
                        await self._conn.send(batch[0])
                    else:
                        await self._conn.send({"op": "batch", "msgs": batch})
            except (ConnectionError, OSError, asyncio.CancelledError):
                # the server never acked these: park them for replay after
                # the reconnect (CancelledError covers session teardown
                # catching the drainer mid-send — the popped batch must not
                # vanish). Re-sending something the server did receive is
                # safe: every task message is fenced by (task, instance)
                # and applied at most once.
                self._replay.extend(batch)
                raise

    # --- connection lifecycle -------------------------------------------
    async def run(self) -> None:
        await self._initial_connect()
        logger.info("registered as worker %d", self.worker_id,
                    extra={"worker": self.worker_id})

        import tempfile

        from hyperqueue_tpu.worker.localcomm import LocalCommListener

        self.localcomm = LocalCommListener(self, Path(tempfile.gettempdir()))
        await self.localcomm.start()

        if not self.zero_worker and self.configuration.runner_pool != 0:
            size = self.configuration.runner_pool
            if size < 0:
                # auto: bounded by the configured CPUs AND the physical
                # cores minus one (a 4-lane worker on a 2-core box gains
                # nothing from 4 runners — extra processes just add
                # context-switch pressure, and one core must stay with the
                # worker's event loop; each runner supervises any number
                # of concurrent payloads, the width only bounds how many
                # spawn syscalls overlap)
                import os as _os

                size = max(
                    1, min(self._n_cpus(), (_os.cpu_count() or 2) - 1, 8)
                )
            self.runner_pool = RunnerPool(size)
            # warm in the background: tasks arriving in the first ~0.5 s
            # take the in-loop spawn path instead of waiting on N python
            # interpreter startups
            self._pool_warmup = asyncio.create_task(self.runner_pool.start())
            self._pool_warmup.add_done_callback(
                lambda t: logger.info(
                    "runner pool started (%d warm runners)", size
                ) if not t.cancelled() and t.exception() is None
                else logger.error("runner pool failed to start: %s",
                                  t.exception() if not t.cancelled()
                                  else "cancelled")
            )

        REGISTRY.add_collect_hook(self._collect_metrics)
        if self.profile_hz > 0 and not clock.is_simulated():
            from hyperqueue_tpu.utils import profiler

            profiler.register_plane("runtime")
            self._profiler_started = profiler.start_profiler(self.profile_hz)
        if self.requested_metrics_port is not None:
            from hyperqueue_tpu.utils.metrics import start_metrics_server

            self._metrics_server, self.metrics_port = (
                await start_metrics_server(
                    REGISTRY, self.requested_metrics_port,
                    host=self.metrics_host,
                    probes={
                        "/healthz": self._probe_healthz,
                        "/readyz": self._probe_readyz,
                    },
                )
            )
            logger.info(
                "metrics endpoint on http://%s:%d/metrics (+ /healthz "
                "/readyz)",
                self.metrics_host, self.metrics_port,
            )

        try:
            while True:
                outcome = await self._run_session()
                if outcome == "stop":
                    return
                if outcome == "redirect":
                    if self.running or self.blocked:
                        # a compute raced in between the redirect
                        # handler's idle check and the session teardown:
                        # abort the lend — the running task's uplinks
                        # belong to the HOME shard's journal. Reconnect
                        # home with reattach so the race-delivered task
                        # keeps its single execution.
                        logger.warning(
                            "aborting lend: %d task(s) raced in; "
                            "re-registering with the home shard",
                            len(self.running) + self._n_blocked,
                        )
                        self._redirect_target = None
                        self._redirect.clear()
                        self.configuration.lent_from = -1
                        if not await self._reconnect_with_backoff():
                            return
                        continue
                    # lent to a sibling shard: drop the old identity and
                    # register FRESH with the target shard dir (no
                    # reattach — only idle workers are lent). From here
                    # on, server loss/reconnect handling points at the
                    # NEW shard: if the borrower dies mid-task later, the
                    # worker reattaches to the borrower's successor.
                    self.server_dir = self._redirect_target
                    self._redirect_target = None
                    self._redirect.clear()
                    self.worker_id = 0
                    self.server_uid = ""
                    self._clear_launch_plans()
                    if self._conn:
                        self._conn.close()
                    logger.warning(
                        "lent to shard dir %s; re-registering",
                        self.server_dir,
                    )
                    # the borrower may itself be mid-failover when the
                    # redirect lands: register with the reconnect-style
                    # backoff window, never a single brittle attempt
                    # (the server only lends reconnect-policy workers,
                    # so _initial_connect retries here)
                    await self._initial_connect()
                    logger.info(
                        "registered as worker %d", self.worker_id,
                        extra={"worker": self.worker_id},
                    )
                    continue
                # server lost
                policy = self.configuration.on_server_lost
                if policy == "finish-running":
                    logger.warning(
                        "server lost; finishing running tasks then exiting"
                    )
                    await self._finish_running_then_exit()
                    return
                if policy != "reconnect":
                    logger.warning("server lost; stopping")
                    return
                if not await self._reconnect_with_backoff():
                    logger.error(
                        "could not reconnect within the reconnect window; "
                        "stopping"
                    )
                    return
        finally:
            for rt in self.running.values():
                if rt.launched is not None:
                    rt.launched.kill()
            if self._pool_warmup is not None and not self._pool_warmup.done():
                self._pool_warmup.cancel()
            if self.runner_pool is not None:
                # drain AFTER the kills above: the kill frames must reach
                # the runners before their stdin EOF triggers exit
                await self.runner_pool.close()
            if self.localcomm is not None:
                self.localcomm.close()
            if self._metrics_server is not None:
                self._metrics_server.close()
            REGISTRY.remove_collect_hook(self._collect_metrics)
            if self._profiler_started:
                from hyperqueue_tpu.utils import profiler

                profiler.stop_profiler()
                self._profiler_started = False
            if self._conn:
                self._conn.close()

    async def _initial_connect(self) -> None:
        """First registration. Under `--on-server-lost reconnect` an
        unreachable server is retried with the same jittered backoff and
        `--reconnect-timeout` window as a lost session: a worker whose
        policy is to ride out server restarts must also ride out being
        STARTED during one (autoalloc and chaos soaks race worker startup
        against server crashes all the time). Any other policy keeps the
        fail-fast contract: a bad address dies immediately and visibly."""
        if self.configuration.on_server_lost != "reconnect":
            await self._connect(reattach=False)
            return
        window = self.configuration.reconnect_timeout_secs
        deadline = clock.monotonic() + window if window > 0 else None
        delay = self.RECONNECT_BACKOFF_BASE
        while True:
            try:
                if self.server_dir is not None:
                    # re-resolve every attempt: a server that comes (back)
                    # up lives in a fresh instance dir with fresh ports
                    from hyperqueue_tpu.utils import serverdir

                    access = serverdir.load_access(self.server_dir)
                    self.host = access.host_for_workers()
                    self.port = access.worker_port
                    self.secret_key = access.worker_key_bytes()
                await asyncio.wait_for(
                    self._connect(reattach=False),
                    timeout=self.RECONNECT_ATTEMPT_TIMEOUT,
                )
                return
            except (
                ConnectionError,
                OSError,
                RuntimeError,
                ValueError,  # torn/corrupt access record mid-publish
                AuthError,
                asyncio.IncompleteReadError,
                asyncio.TimeoutError,
            ) as e:
                now = clock.monotonic()
                limit = self.configuration.time_limit_secs
                if limit > 0 and now - self.started_at >= limit:
                    raise  # same contract as _reconnect_with_backoff
                if deadline is not None and now >= deadline:
                    raise
                sleep_for, delay = jittered_backoff(
                    delay, self.RECONNECT_BACKOFF_CAP, self._rng,
                    remaining=(
                        deadline - now if deadline is not None else None
                    ),
                )
                logger.info(
                    "server unreachable at first registration (%s); "
                    "retrying in %.2fs", e, sleep_for,
                )
                await asyncio.sleep(sleep_for)

    async def _connect(self, reattach: bool) -> None:
        """One connect + register handshake; sets self._conn on success.

        With `reattach`, the register message carries the previous identity
        and the still-running (task, instance) set so the server can
        reattach what it held for us; the `registered` reply then names the
        stale incarnations to kill."""
        host, port, key = self.host, self.port, self.secret_key
        if reattach and self.server_dir is not None:
            # re-resolve from the server dir: a restarted server lives in a
            # NEW instance dir with fresh ports and plane keys
            from hyperqueue_tpu.utils import serverdir

            access = serverdir.load_access(self.server_dir)
            host = access.host_for_workers()
            port = access.worker_port
            key = access.worker_key_bytes()
        reader, writer = await asyncio.open_connection(host, port)
        try:
            conn, registered = await self._handshake(reader, writer, key, reattach)
        except BaseException:
            # covers cancellation by the per-attempt timeout too: never
            # leak a half-authenticated socket per failed attempt
            writer.close()
            raise
        self._apply_registration(registered, host, port, key, conn, reattach)

    async def _handshake(self, reader, writer, key, reattach: bool):
        conn = await do_authentication(
            reader, writer, ROLE_WORKER, ROLE_SERVER, key
        )
        register: dict = {
            "op": "register",
            "config": self.configuration.to_wire(),
        }
        if reattach:
            register["reattach"] = {
                "worker_id": self.worker_id,
                "server_uid": self.server_uid,
                "running": [
                    {"id": task_id, "instance": rt.msg.get("instance", 0),
                     # the variant actually executing: the server needs it
                     # to account the right resource amounts when it never
                     # journaled this task's start
                     "variant": rt.msg.get("variant", 0)}
                    for task_id, rt in self.running.items()
                ],
                # parked-but-never-started tasks must be declared too: the
                # restored server re-issues them (no task-started was ever
                # journaled) and a silently-kept local copy would execute
                # alongside the re-issue under the SAME instance id —
                # invisible to the fence. The server always discards these.
                "blocked": [
                    {"id": t["id"], "instance": t.get("instance", 0)}
                    for group in self.blocked.values()
                    for t in group
                ],
            }
        await conn.send(register)
        registered = await conn.recv()
        if registered.get("op") != "registered":
            raise RuntimeError(f"registration failed: {registered}")
        return conn, registered

    def _apply_registration(
        self, registered: dict, host, port, key, conn, reattach: bool
    ) -> None:
        self.worker_id = registered["worker_id"]
        self.server_uid = registered.get("server_uid", "")
        if self.configuration.idle_timeout_secs < 0:
            # --idle-timeout not given: adopt the server-wide default
            # (reference tako rpc.rs:130 sync_worker_configuration). An
            # explicit --idle-timeout 0 opts out and is left alone.
            self.configuration.idle_timeout_secs = float(
                registered.get("server_idle_timeout") or 0.0
            )
        self.host, self.port, self.secret_key = host, port, key
        self._conn = conn
        self._session_live = True
        if reattach:
            # plans embed the (now stale) worker id and server uid
            self._clear_launch_plans()
            discard = registered.get("discard") or []
            for task_id in discard:
                # the server refused this incarnation (requeued under a
                # newer instance, already terminal, or never held): kill it
                # so a rescheduled copy elsewhere stays the only execution
                logger.warning(
                    "task %d is stale after reconnect; killing it", task_id
                )
                if task_id in self.running:
                    self._discarded.add(task_id)
                self._cancel_task(task_id)
            if discard:
                # forget the discarded incarnations: the restored server
                # may legitimately re-issue one of these (task, instance)
                # pairs (it never saw them start), and the dedup guard
                # must not swallow the re-delivery
                dropped = set(discard)
                self._recent_tasks = OrderedDict(
                    (k, None) for k in self._recent_tasks
                    if k[0] not in dropped
                )
            logger.warning(
                "reconnected as worker %d (%d task(s) reattached, "
                "%d stale discarded)",
                self.worker_id,
                len(registered.get("reattached") or ()),
                len(discard),
            )

    async def _reconnect_with_backoff(self) -> bool:
        """Retry the handshake with jittered exponential backoff; running
        tasks keep executing (and queue their results) throughout. Returns
        False once the reconnect window (`--reconnect-timeout`, 0 = keep
        trying forever) or the worker time limit is exhausted."""
        window = self.configuration.reconnect_timeout_secs
        deadline = clock.monotonic() + window if window > 0 else None
        delay = self.RECONNECT_BACKOFF_BASE
        attempt = 0
        while True:
            attempt += 1
            _RECONNECT_ATTEMPTS.inc()
            try:
                await asyncio.wait_for(
                    self._connect(reattach=True),
                    timeout=self.RECONNECT_ATTEMPT_TIMEOUT,
                )
                _RECONNECTS.inc()
                return True
            except (
                ConnectionError,
                OSError,
                RuntimeError,
                # ValueError covers a torn/corrupt access record mid-publish
                # (json decode errors subclass it)
                ValueError,
                AuthError,
                asyncio.IncompleteReadError,
                asyncio.TimeoutError,
            ) as e:
                now = clock.monotonic()
                limit = self.configuration.time_limit_secs
                if limit > 0 and now - self.started_at >= limit:
                    logger.warning("time limit reached while reconnecting")
                    return False
                if deadline is not None and now >= deadline:
                    logger.warning("reconnect attempt %d failed: %s", attempt, e)
                    return False
                sleep_for, delay = jittered_backoff(
                    delay, self.RECONNECT_BACKOFF_CAP, self._rng,
                    remaining=(
                        deadline - now if deadline is not None else None
                    ),
                )
                logger.info(
                    "reconnect attempt %d failed (%s); retrying in %.2fs",
                    attempt, e, sleep_for,
                )
                await asyncio.sleep(sleep_for)

    def _rebuild_sendq(self) -> None:
        """Order the next session's uplink queue: replayed final messages
        first (oldest news), then unsent parked messages, then whatever was
        queued while disconnected. Heartbeats/overviews are dropped — they
        describe a dead connection's moment in time."""
        items: list[dict] = list(self._done_log.values())
        items.extend(self._replay)
        # messages past this index were merely queued while disconnected —
        # they are first sends, not replays, and don't count as such
        n_replay_candidates = len(items)
        self._done_log.clear()
        self._replay = []
        while True:
            try:
                items.append(self._sendq.get_nowait())
            except asyncio.QueueEmpty:
                break
        fresh: asyncio.Queue = asyncio.Queue()
        seen: set[int] = set()
        replayed = 0
        for i, msg in enumerate(items):
            if msg.get("op") in ("heartbeat", "overview"):
                continue
            if id(msg) in seen:
                continue  # same dict parked via both _done_log and _replay
            seen.add(id(msg))
            if i < n_replay_candidates:
                replayed += 1
            fresh.put_nowait(msg)
        # counted HERE — messages actually re-sent after this reconnect —
        # not at park time: a park-then-drop (heartbeat) or a re-park on a
        # flapping connection must not inflate the replay count
        if replayed:
            _REPLAYED.inc(replayed)
        self._sendq = fresh

    async def _run_session(self) -> str:
        """Drive one connected session; returns "stop" (deliberate exit)
        or "lost" (connection failure)."""
        self._rebuild_sendq()
        tasks = [
            asyncio.create_task(self._message_loop()),
            asyncio.create_task(self._send_drainer()),
            asyncio.create_task(self._heartbeat_loop()),
            asyncio.create_task(self._limits_loop()),
        ]
        # always started: the server can force overviews on at any time
        # while a dashboard listens (set_overview_override)
        tasks.append(asyncio.create_task(self._overview_loop()))
        stop_wait = asyncio.create_task(self._stop.wait())
        redirect_wait = asyncio.create_task(self._redirect.wait())
        waiters = (stop_wait, redirect_wait)
        try:
            done, _pending = await asyncio.wait(
                tasks + list(waiters), return_when=asyncio.FIRST_COMPLETED
            )
            for t in done:
                if t not in waiters and t.exception():
                    raise t.exception()
            if self._redirect.is_set() and not self._stop.is_set():
                return "redirect"
            return "stop"
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            logger.warning("server connection lost (%s)", e)
            return "lost"
        finally:
            self._session_live = False
            for t in tasks + list(waiters):
                t.cancel()
            await asyncio.gather(*tasks, *waiters, return_exceptions=True)
            if self._conn:
                self._conn.close()

    async def _finish_running_then_exit(self) -> None:
        while self.running:
            await asyncio.sleep(0.1)

    async def _message_loop(self) -> None:
        while True:
            msg = await self._conn.recv()
            # the server coalesces bursts (assignment batches, retract
            # fan-out) into one batch frame; chaos actions keep applying
            # per LOGICAL message so fault plans targeting e.g. `compute`
            # behave identically under batching
            subs = msg["msgs"] if msg.get("op") == "batch" else (msg,)
            for sub in subs:
                action = None
                if chaos.ACTIVE:
                    action = await chaos.on_message(
                        "worker.recv", op=sub.get("op")
                    )
                    if action == "drop":
                        continue
                if await self._handle_server_message(sub):
                    return
                if action == "dup" and await self._handle_server_message(sub):
                    return

    async def _handle_server_message(self, msg: dict) -> bool:
        """Process one server message; True = stop requested."""
        op = msg.get("op")
        if op == "compute":
            shared = msg.get("shared_bodies")
            shared_tr = msg.get("shared_traces")
            for task_msg in msg["tasks"]:
                if shared is not None and "b" in task_msg:
                    # resolve the shared/separate split; the body dict
                    # stays shared between tasks (read-only downstream)
                    task_msg["body"] = shared[task_msg.pop("b")]
                tr = task_msg.get("trace")
                if (
                    shared_tr is not None
                    and isinstance(tr, list)
                    and tr
                    and isinstance(tr[0], int)
                ):
                    # resolve the frame-level trace-id dedup
                    task_msg["trace"] = [
                        shared_tr[tr[0]], tr[1] if len(tr) > 1 else None,
                    ]
                key = (task_msg["id"], task_msg.get("instance", 0))
                if key in self._recent_tasks:
                    # duplicate delivery of the same incarnation (chaos
                    # dup, or a replayed server send queue): never run a
                    # task twice
                    logger.warning(
                        "dropping duplicate compute for task %d instance %d",
                        key[0], key[1],
                    )
                    continue
                self._recent_tasks[key] = None
                while len(self._recent_tasks) > self.RECENT_TASKS_MAX:
                    self._recent_tasks.popitem(last=False)
                tctx = read_trace(task_msg)
                if tctx is not None:
                    # distributed trace (server-side assembly): normalize
                    # the compact wire header, stamp the accept clock;
                    # launch/spawn clocks follow in _run_task and
                    # everything is echoed on the task_running uplink
                    tctx["accepted_at"] = clock.now()
                    task_msg["trace"] = tctx
                self._try_start(task_msg)
        elif op == "cancel":
            for task_id in msg["task_ids"]:
                self._cancel_task(task_id)
        elif op == "retract":
            for task_id, instance in msg["tasks"]:
                # retract may only reclaim NOT-YET-STARTED tasks: remove
                # from the blocked queue, never touch running ones (the
                # server treats ok=False as "it started, leave it be").
                # The instance is echoed so the server can discard stale
                # answers, like every other task message.
                before = self._n_blocked
                self._remove_blocked(task_id)
                await self._send(
                    {
                        "op": "retract_response",
                        "id": task_id,
                        "instance": instance,
                        "ok": self._n_blocked < before,
                    }
                )
        elif op == "set_overview_override":
            interval = msg.get("interval")
            self._overview_override = (
                float(interval) if interval is not None else None
            )
            self._overview_wake.set()
        elif op == "redirect":
            # federation lending: re-register with a sibling shard. The
            # target is derived from OUR OWN server dir (shard dirs are
            # siblings under the federation root) — the server never
            # dictates filesystem paths across hosts.
            from hyperqueue_tpu.utils import serverdir as _serverdir

            target = int(msg.get("shard", -1))
            if self.server_dir is None or (
                _serverdir.shard_id_of(self.server_dir) is None
            ):
                logger.warning(
                    "ignoring redirect to shard %d: this worker was not "
                    "started against a federation shard dir", target,
                )
            elif self.configuration.on_server_lost != "reconnect":
                # a lent worker must ride out the borrower dying later;
                # the server checks this too — refuse defensively
                logger.warning(
                    "ignoring redirect to shard %d: --on-server-lost is "
                    "not 'reconnect'", target,
                )
            elif self.running or self.blocked:
                # the server only lends idle workers, but a task may have
                # raced in; refuse rather than strand its uplinks
                logger.warning(
                    "ignoring redirect to shard %d: %d task(s) running",
                    target, len(self.running),
                )
            else:
                self._redirect_target = _serverdir.shard_path(
                    self.server_dir.parent, target
                )
                # remember the home shard so the borrower can count its
                # borrowed pool (register config carries it)
                home = _serverdir.shard_id_of(self.server_dir)
                self.configuration.lent_from = int(
                    msg.get("from_shard", home if home is not None else -1)
                )
                self._redirect.set()
        elif op == "stop":
            self._stop.set()
            return True
        else:
            logger.warning("unknown server message %r", op)
        return False

    def _park(self, sig: tuple, task_msg: dict) -> None:
        """Park a task in its signature group, ordered by priority
        (descending, stable): a later high-priority compute message must
        start before earlier low-priority backlog once resources free up —
        the server-side analog is the displacement retract (reference
        test_reactor.rs test_prefill_submit_high_priority)."""
        group = self.blocked.setdefault(sig, [])
        priority = tuple(task_msg.get("priority") or (0, 0))
        idx = len(group)
        while idx > 0 and tuple(group[idx - 1].get("priority") or (0, 0)) < priority:
            idx -= 1
        group.insert(idx, task_msg)
        self._n_blocked += 1

    def _try_start(self, task_msg: dict) -> bool:
        """Returns False if the task was parked in the blocked queue."""
        entries = task_msg.get("entries", [])
        sig = self._entries_sig(task_msg) if entries else ()
        if entries and sig in self.blocked:
            # peers with the same signature are already waiting: the head
            # could not allocate, so this one cannot either — park without
            # probing
            self._park(sig, task_msg)
            return False
        allocation = self.allocator.try_allocate(entries)
        if allocation is None and entries:
            logger.debug("task %d blocked on resources", task_msg["id"],
                         extra={"task": task_msg["id"]})
            self._park(sig, task_msg)
            return False
        self._start_with_allocation(task_msg, allocation)
        return True

    def _start_with_allocation(self, task_msg: dict, allocation) -> None:
        body = task_msg.get("body") or {}
        if (
            self.zero_worker
            and not body.get("stream")
            and not body.get("time_limit")
        ):
            # zero-worker fast path: no process ever exists, so completing
            # inline (two queued uplinks, immediate release) skips the
            # per-task coroutine + future + RunningTask entirely — the
            # worker-side floor of the <0.1 ms/task overhead target
            task_id = task_msg["id"]
            instance = task_msg.get("instance", 0)
            self._sendq.put_nowait(
                {"op": "task_running", "id": task_id, "instance": instance}
            )
            self._sendq.put_nowait(
                {"op": "task_finished", "id": task_id, "instance": instance}
            )
            _TASKS_DONE.labels("finished").inc()
            self.last_task_time = clock.monotonic()
            if allocation is not None:
                self.allocator.release(allocation)
                if self.blocked:
                    # re-probe parked tasks — but via call_soon: this fast
                    # path runs inside _retry_blocked itself, and a direct
                    # call would recurse one frame per blocked task
                    asyncio.get_running_loop().call_soon(self._retry_blocked)
            return
        future = asyncio.create_task(self._run_task(task_msg, allocation))
        self.running[task_msg["id"]] = RunningTask(
            task_msg, allocation, None, future
        )

    async def _run_task(self, task_msg: dict, allocation) -> None:
        task_id = task_msg["id"]
        instance = task_msg.get("instance", 0)
        held_stream_dir = None
        try:
            streamer = None
            body = task_msg.get("body") or {}
            stream_dir = body.get("stream")
            if stream_dir:
                # stream paths carry JOB-scope placeholders (reference
                # test_placeholders.py stream_submit_placeholder); task-
                # scope ones are a hard submit-time error
                # (cli._check_submit_placeholders) — a stream dir is
                # shared by the whole job
                import os as _os

                from hyperqueue_tpu.ids import task_id_job
                from hyperqueue_tpu.utils.placeholders import (
                    fill_placeholders,
                )

                stream_dir = fill_placeholders(stream_dir, {
                    "JOB_ID": str(task_id_job(task_id)),
                    "SUBMIT_DIR": body.get("submit_dir") or _os.getcwd(),
                    "SERVER_UID": self.server_uid,
                })
                streamer = self._acquire_streamer(stream_dir)
                held_stream_dir = stream_dir
            extra_env = {}
            if self.localcomm is not None:
                extra_env["HQ_LOCAL_SOCKET"] = self.localcomm.socket_path
                extra_env["HQ_TOKEN"] = self.localcomm.register_task(task_id)
            tctx = task_msg.get("trace")
            if tctx is not None:
                tctx["launch_at"] = clock.now()
            _t_spawn = time.perf_counter()
            launched = await self._launch(
                task_msg, allocation, streamer, extra_env
            )
            _SPAWN_SECONDS.observe(time.perf_counter() - _t_spawn)
            if tctx is not None:
                # the true spawn clock when the handle recorded one (runner
                # ack / in-loop subprocess); dispatch-complete otherwise
                tctx["spawned_at"] = (
                    getattr(launched, "spawned_wall", 0.0) or clock.now()
                )
            rt = self.running.get(task_id)
            if rt is not None:
                rt.launched = launched
            running_msg = {
                "op": "task_running", "id": task_id, "instance": instance,
            }
            if tctx is not None:
                running_msg["trace"] = {
                    "id": tctx.get("id"),
                    "parent": tctx.get("parent"),
                    "accepted_at": tctx.get("accepted_at"),
                    "launch_at": tctx.get("launch_at"),
                    "spawned_at": tctx.get("spawned_at"),
                }
            await self._send(running_msg)
            # per-task time limit (reference: task futures carry stop
            # reasons; program.rs timeout path): kill and fail on expiry
            time_limit = (task_msg.get("body") or {}).get("time_limit")
            timed_out = False
            if time_limit:
                # arm the limit at the true spawn (the runner acks it for
                # pool launches), not at dispatch: time queued behind other
                # spawns in a backlogged runner is not the task's runtime
                await launched.started()
                try:
                    code, detail = await asyncio.wait_for(
                        launched.wait(), timeout=float(time_limit)
                    )
                except asyncio.TimeoutError:
                    timed_out = True
                    launched.kill()
                    await launched.wait()
                    code, detail = -1, ""
            else:
                code, detail = await launched.wait()
            if tctx is not None:
                tctx["exited_at"] = clock.now()
            if task_id in self._discarded:
                # killed as a stale incarnation at reconnect: exit silently
                # (a report could pass the fence against a re-issued copy
                # running elsewhere under the same instance id)
                if streamer is not None:
                    streamer.close_task(task_id, instance)
                return
            if timed_out:
                if streamer is not None:
                    streamer.close_task(task_id, instance)
                _TASKS_DONE.labels("timeout").inc()
                msg = {
                    "op": "task_failed",
                    "id": task_id,
                    "instance": instance,
                    "error": f"time limit of {time_limit}s exceeded",
                }
                self._attach_finish_trace(msg, tctx)
                await self._send(msg)
                return
            if streamer is not None:
                streamer.close_task(task_id, instance)
            _TASKS_DONE.labels("finished" if code == 0 else "failed").inc()
            if code == 0:
                msg = {
                    "op": "task_finished", "id": task_id, "instance": instance,
                }
                self._attach_finish_trace(msg, tctx)
                await self._send(msg)
            else:
                error = f"program exited with code {code}"
                if detail:
                    error += f"\nstderr (tail):\n{detail}"
                msg = {
                    "op": "task_failed",
                    "id": task_id,
                    "instance": instance,
                    "error": error,
                }
                self._attach_finish_trace(msg, tctx)
                await self._send(msg)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 - report, don't kill the worker
            logger.exception("task %d launch failed", task_id,
                             extra={"task": task_id})
            if task_id not in self._discarded:
                try:
                    msg = {
                        "op": "task_failed",
                        "id": task_id,
                        "instance": instance,
                        "error": f"failed to launch: {e}",
                    }
                    self._attach_finish_trace(msg, task_msg.get("trace"))
                    await self._send(msg)
                except (ConnectionError, OSError):
                    pass
        finally:
            self._discarded.discard(task_id)
            self.last_task_time = clock.monotonic()
            if held_stream_dir is not None:
                self._release_streamer(held_stream_dir)
            if self.localcomm is not None:
                self.localcomm.unregister_task(task_id)
            rt = self.running.pop(task_id, None)
            if rt is not None and rt.allocation is not None:
                self.allocator.release(rt.allocation)
            self._retry_blocked()

    @staticmethod
    def _attach_finish_trace(msg: dict, tctx: dict | None) -> None:
        """Echo the trace context + completion clocks on a terminal uplink.

        spawned_at rides AGAIN (it already went out on task_running) so a
        restarted server whose journal lost the start event in its
        unflushed tail can still close the trace with the execution span
        intact; sent_at is the uplink-enqueue clock — the worker-side end
        of the uplink span the server closes at receive time."""
        if tctx is None:
            return
        now = clock.now()
        msg["trace"] = {
            "id": tctx.get("id"),
            "parent": tctx.get("parent"),
            "spawned_at": tctx.get("spawned_at"),
            "exited_at": tctx.get("exited_at") or now,
            "sent_at": now,
        }

    # --- dispatch: runner pool fast path vs in-loop asyncio spawn --------
    MAX_LAUNCH_PLANS = 512

    def _n_cpus(self) -> int:
        from hyperqueue_tpu.resources.amount import FRACTIONS_PER_UNIT
        from hyperqueue_tpu.resources.descriptor import DescriptorKind

        for item in self.configuration.descriptor.items:
            if item.name != "cpus":
                continue
            if item.kind is DescriptorKind.SUM:
                return max(1, item.sum_size // FRACTIONS_PER_UNIT)
            return max(1, sum(len(g) for g in item.index_groups()))
        return 1

    async def _launch(self, task_msg, allocation, streamer, extra_env):
        """Route a launch: warm runner pool for plain process tasks, the
        in-loop asyncio path for stream/stdin/gang tasks, zero-worker mode
        and a broken pool."""
        pool = self.runner_pool
        if (
            pool is not None
            and pool.available
            and not self.zero_worker
            and streamer is None
            and poolable(task_msg)
        ):
            plan = self._launch_plan(task_msg)
            spec = plan.instantiate(task_msg, allocation, extra_env)
            try:
                # no spawn ack on the hot path: the dispatch frame IS the
                # start (the runner spawns in-order), so the exit frame
                # stays the only per-task runner->worker wakeup; a spawn
                # failure surfaces through wait() as SpawnFailed. Tasks
                # with a time limit opt into the ack so the limit timer
                # arms at the real spawn, not at dispatch.
                ack = bool(
                    (task_msg.get("body") or {}).get("time_limit")
                )
                return await pool.launch(plan, spec, ack=ack)
            except RunnerCrashed:
                # pool raced into unavailability between the check and the
                # dispatch: this task still launches, just in-loop
                logger.warning(
                    "runner pool unavailable; task %d falls back to "
                    "in-loop spawn", task_msg["id"],
                )
        return await launch_task(
            task_msg,
            allocation,
            server_uid=self.server_uid,
            worker_id=self.worker_id,
            zero_worker=self.zero_worker,
            streamer=streamer,
            extra_env=extra_env,
        )

    def _launch_plan(self, task_msg) -> LaunchPlan:
        """Get-or-build the launch plan for this task's (program, env
        template, stdio shape). Keyed by the job and the IDENTITY of the
        shared body dict: an array's tasks share one body object on the
        wire, while a task submitted with different env/cwd/stdio carries a
        different body and therefore never reuses a stale plan."""
        key = (task_id_job(task_msg["id"]), id(task_msg.get("body")))
        plan = self._plan_cache.get(key)
        if plan is not None:
            _PLAN_LOOKUPS.labels("hit").inc()
            self._plan_cache.move_to_end(key)
            return plan
        _PLAN_LOOKUPS.labels("miss").inc()
        static_env = {}
        if self.localcomm is not None:
            static_env["HQ_LOCAL_SOCKET"] = self.localcomm.socket_path
        plan = LaunchPlan(
            task_msg, self.server_uid, self.worker_id, static_env=static_env
        )
        self._plan_cache[key] = plan
        while len(self._plan_cache) > self.MAX_LAUNCH_PLANS:
            _, evicted = self._plan_cache.popitem(last=False)
            self._drop_plan(evicted)
        return plan

    def _drop_plan(self, plan: LaunchPlan) -> None:
        if self.runner_pool is None:
            return
        for runner in self.runner_pool.runners:
            if plan.plan_id in runner.known_plans:
                runner.known_plans.discard(plan.plan_id)
                try:
                    runner.send({"op": "drop_plan", "plan": plan.plan_id})
                except (ConnectionError, OSError):
                    pass

    def _clear_launch_plans(self) -> None:
        """Reconnect invalidates every plan: plans embed HQ_WORKER_ID and
        the server uid, both of which change with the new registration."""
        for plan in self._plan_cache.values():
            self._drop_plan(plan)
        self._plan_cache.clear()

    # keep this many stream writers' fds open at most; in-use writers are
    # never closed, so the bound can be exceeded while > MAX distinct
    # stream dirs have running tasks
    MAX_STREAM_WRITERS = 64

    def _acquire_streamer(self, stream_dir: str):
        """Get-or-open the StreamWriter for a stream dir and hold a
        refcount on it for a running task.

        Eviction closes only ZERO-refcount writers (closing one under a
        running task fails that task's next write_chunk/close_task), in
        least-recently-USED order: reused dirs move to the end of the
        dict, so insertion order is true LRU order.  Pair every call with
        _release_streamer."""
        streamer = self._streamers.get(stream_dir)
        if streamer is not None:
            self._streamers.pop(stream_dir)
            self._streamers[stream_dir] = streamer
        else:
            from hyperqueue_tpu.events.outputlog import StreamWriter

            # bound open fds: per-job stream dirs accumulate on a
            # long-lived worker.  If every writer is in use the bound is
            # exceeded rather than an in-flight task's writer closed.
            while len(self._streamers) >= self.MAX_STREAM_WRITERS:
                victim = next(
                    (
                        d for d in self._streamers
                        if not self._streamer_users.get(d)
                    ),
                    None,
                )
                if victim is None:
                    break
                self._streamers.pop(victim).close()
            streamer = StreamWriter(
                stream_dir, self.worker_id, self.server_uid
            )
            self._streamers[stream_dir] = streamer
        self._streamer_users[stream_dir] = (
            self._streamer_users.get(stream_dir, 0) + 1
        )
        return streamer

    def _release_streamer(self, stream_dir: str) -> None:
        remaining = self._streamer_users.get(stream_dir, 1) - 1
        if remaining > 0:
            self._streamer_users[stream_dir] = remaining
        else:
            self._streamer_users.pop(stream_dir, None)

    @staticmethod
    def _entries_sig(task_msg: dict):
        return tuple(
            (e["name"], e["amount"], e.get("policy", "compact"))
            for e in task_msg.get("entries", [])
        )

    def _retry_blocked(self) -> None:
        """Retry blocked tasks after a resource release.

        Blocked tasks are bucketed by resource signature; identical
        signatures fail identically, so each release only probes one head
        per signature group — O(#signatures), not O(#blocked), per release
        (the deep prefill queue made the naive scan the worker's dominant
        cost at 50k+ short tasks).  Signature groups are probed in
        head-priority order so a freed resource goes to the
        highest-priority waiter."""
        for sig in sorted(
            self.blocked,
            key=lambda s: tuple(self.blocked[s][0].get("priority") or (0, 0)),
            reverse=True,
        ):
            group = self.blocked.get(sig)
            while group:
                task_msg = group[0]
                allocation = self.allocator.try_allocate(
                    task_msg.get("entries", [])
                )
                if allocation is None:
                    break
                group.pop(0)
                self._n_blocked -= 1
                self._start_with_allocation(task_msg, allocation)
            if not group:
                self.blocked.pop(sig, None)

    def _remove_blocked(self, task_id: int) -> None:
        for sig, group in list(self.blocked.items()):
            kept = [t for t in group if t["id"] != task_id]
            self._n_blocked -= len(group) - len(kept)
            if kept:
                self.blocked[sig] = kept
            else:
                self.blocked.pop(sig, None)

    def _cancel_task(self, task_id: int) -> None:
        self._remove_blocked(task_id)
        rt = self.running.get(task_id)
        if rt is not None:
            if rt.launched is not None:
                rt.launched.kill()
            else:
                rt.future.cancel()

    async def _overview_loop(self) -> None:
        """Send hw telemetry on the configured cadence — or on the
        server-forced one while a dashboard listens (reference
        SetOverviewIntervalOverride, messages/worker.rs:76-165, applied in
        worker/rpc.rs:394-396)."""
        from hyperqueue_tpu.worker.hwmonitor import HwSampler

        sampler = HwSampler()
        while True:
            interval = (
                self._overview_override
                if self._overview_override is not None
                else self.configuration.overview_interval_secs
            )
            self._overview_wake.clear()
            if interval <= 0:
                # overviews disabled: park until an override arrives
                await self._overview_wake.wait()
                continue
            try:
                # an arriving override interrupts the wait so a dashboard
                # gets telemetry immediately even under a long configured
                # interval (and detach restores the old cadence at once)
                await asyncio.wait_for(
                    self._overview_wake.wait(), timeout=interval
                )
                continue  # re-read the effective interval
            except asyncio.TimeoutError:
                pass  # cadence elapsed: sample and send
            # sampling shells out to nvidia-smi/rocm-smi (blocking, up to
            # seconds on a wedged driver); keep it off the event loop so
            # heartbeats and task messaging never stall
            hw = await asyncio.to_thread(sampler.sample)
            self._fold_hw_gauges(hw)
            await self._send(
                {
                    "op": "overview",
                    "hw": hw,
                    "n_running": len(self.running),
                    # gauge/counter samples ride along so the server can
                    # re-export a cluster-wide view with a `worker` label
                    # (and the dashboard reads gauges, not raw hw dicts)
                    "metrics": REGISTRY.export_samples(prefix="hq_worker_"),
                }
            )

    def _fold_hw_gauges(self, hw: dict) -> None:
        """HwSampler output -> hq_worker_* gauges (labels per GPU)."""
        _CPU.set(hw.get("cpu_usage_percent", 0.0))
        _MEM_TOTAL.set(hw.get("mem_total_bytes", 0))
        _MEM_AVAILABLE.set(hw.get("mem_available_bytes", 0))
        _LOAD.set(hw.get("loadavg_1m", 0.0))
        if "gpus" in hw:
            # clear even on an empty sample (transient nvidia-smi/rocm-smi
            # failure returns []): stale per-GPU series must not keep
            # exporting dead utilization as live
            _GPU.clear()
            _GPU_MEM.clear()
            for gpu in hw["gpus"]:
                gid = str(gpu.get("id", ""))
                _GPU.labels(gid).set(gpu.get("usage_percent", 0.0))
                _GPU_MEM.labels(gid).set(gpu.get("mem_usage_percent", 0.0))

    async def _heartbeat_loop(self) -> None:
        interval = max(self.configuration.heartbeat_secs, 0.5)
        while True:
            await asyncio.sleep(interval)
            await self._send({"op": "heartbeat"})

    # ---- health probes (ISSUE 18) ------------------------------------
    # Served by the metrics endpoint on the worker's own event loop: a
    # wedged loop simply cannot answer, so a 200 is evidence the process
    # is actually turning over, not just that a socket is bound.

    def _probe_healthz(self):
        return True, {"role": "worker", "worker_id": self.worker_id}

    def _probe_readyz(self):
        checks = {
            # between sessions (server died, reconnect backoff running)
            # the worker must drop out of rotation: it cannot accept work
            "session": "ok" if self._session_live else "disconnected",
            "stopping": "ok" if not self._stop.is_set() else "stopping",
        }
        ok = all(v == "ok" for v in checks.values())
        return ok, {"role": "worker", "worker_id": self.worker_id,
                    "checks": checks}

    async def _goodbye(self, reason: str) -> None:
        """Tell the server this is a DELIBERATE exit (idle/time limit), so
        requeued tasks don't get charged a crash (reference CrashLimit:
        stops and time limits don't count). Sent directly — the batching
        drainer may never run again once _stop is set."""
        try:
            async with self._send_lock:
                await self._conn.send({"op": "goodbye", "reason": reason})
        except (ConnectionError, OSError):
            pass

    async def _limits_loop(self) -> None:
        while True:
            await asyncio.sleep(0.5)
            now = clock.monotonic()
            limit = self.configuration.time_limit_secs
            if limit > 0 and now - self.started_at >= limit:
                logger.info("time limit reached; stopping")
                await self._goodbye("time limit")
                self._stop.set()
                return
            idle = self.configuration.idle_timeout_secs
            if (
                idle > 0
                and not self.running
                and not self.blocked
                and now - self.last_task_time >= idle
            ):
                logger.info("idle timeout reached; stopping")
                await self._goodbye("idle timeout")
                self._stop.set()
                return


async def run_worker(
    host: str,
    port: int,
    secret_key: bytes | None,
    configuration: WorkerConfiguration,
    zero_worker: bool = False,
    server_dir: Path | None = None,
    metrics_port: int | None = None,
    metrics_host: str = "0.0.0.0",
    profile_hz: float = 19.0,
) -> None:
    runtime = WorkerRuntime(
        host, port, secret_key, configuration, zero_worker=zero_worker,
        server_dir=server_dir, metrics_port=metrics_port,
        metrics_host=metrics_host, profile_hz=profile_hz,
    )
    await runtime.run()
