"""Worker runtime: connect, register, run tasks, report results.

Reference: crates/tako/src/internal/worker/rpc.rs (run_worker) — a select loop
over the server message stream, heartbeat timer, idle timeout and time limit;
plus worker/reactor.rs (compute_tasks -> try_start_task -> launch). Tasks that
cannot allocate resources right now (fractional packing races) sit in a
blocked queue retried after every release.
"""

from __future__ import annotations

import asyncio
import logging
import time
from pathlib import Path

from hyperqueue_tpu.server.worker import WorkerConfiguration
from hyperqueue_tpu.transport.auth import (
    ROLE_SERVER,
    ROLE_WORKER,
    Connection,
    do_authentication,
)
from hyperqueue_tpu.worker.allocator import ResourceAllocator
from hyperqueue_tpu.worker.launcher import LaunchedTask, launch_task

logger = logging.getLogger("hq.worker")


class RunningTask:
    __slots__ = ("msg", "allocation", "launched", "future")

    def __init__(self, msg, allocation, launched, future):
        self.msg = msg
        self.allocation = allocation
        self.launched: LaunchedTask = launched
        self.future: asyncio.Task = future


class WorkerRuntime:
    def __init__(
        self,
        host: str,
        port: int,
        secret_key: bytes | None,
        configuration: WorkerConfiguration,
        zero_worker: bool = False,
    ):
        self.host = host
        self.port = port
        self.secret_key = secret_key
        self.configuration = configuration
        self.zero_worker = zero_worker
        self.allocator = ResourceAllocator(configuration.descriptor)
        self.worker_id = 0
        self.server_uid = ""
        self.running: dict[int, RunningTask] = {}
        # resource-signature -> list of blocked task messages (FIFO)
        self.blocked: dict[tuple, list[dict]] = {}
        self._n_blocked = 0
        self._streamers: dict[str, object] = {}  # stream dir -> StreamWriter
        # stream dir -> number of RUNNING tasks currently holding the
        # writer: eviction may only close zero-refcount writers (closing an
        # in-use one fails its task's next write_chunk/close_task)
        self._streamer_users: dict[str, int] = {}
        self.last_task_time = time.monotonic()
        self.started_at = time.monotonic()
        self._conn: Connection | None = None
        self._send_lock = asyncio.Lock()
        self._sendq: asyncio.Queue = asyncio.Queue()
        self._stop = asyncio.Event()
        # server-forced overview cadence (None = use configuration)
        self._overview_override: float | None = None
        self._overview_wake = asyncio.Event()
        self.localcomm = None

    async def _send(self, msg: dict) -> None:
        """Enqueue an uplink message; a drainer batches queued messages into
        one frame (one encryption + one syscall for a burst of task events —
        the per-task overhead win analogous to the reference's shared/
        separate compute-message split, messages/worker.rs:28-54)."""
        self._sendq.put_nowait(msg)

    async def _send_drainer(self) -> None:
        while True:
            msg = await self._sendq.get()
            batch = [msg]
            while len(batch) < 512:
                try:
                    batch.append(self._sendq.get_nowait())
                except asyncio.QueueEmpty:
                    break
            async with self._send_lock:
                if len(batch) == 1:
                    await self._conn.send(batch[0])
                else:
                    await self._conn.send({"op": "batch", "msgs": batch})

    async def run(self) -> None:
        reader, writer = await asyncio.open_connection(self.host, self.port)
        self._conn = await do_authentication(
            reader, writer, ROLE_WORKER, ROLE_SERVER, self.secret_key
        )
        await self._conn.send(
            {"op": "register", "config": self.configuration.to_wire()}
        )
        registered = await self._conn.recv()
        if registered.get("op") != "registered":
            raise RuntimeError(f"registration failed: {registered}")
        self.worker_id = registered["worker_id"]
        self.server_uid = registered.get("server_uid", "")
        if self.configuration.idle_timeout_secs < 0:
            # --idle-timeout not given: adopt the server-wide default
            # (reference tako rpc.rs:130 sync_worker_configuration). An
            # explicit --idle-timeout 0 opts out and is left alone.
            self.configuration.idle_timeout_secs = float(
                registered.get("server_idle_timeout") or 0.0
            )
        logger.info("registered as worker %d", self.worker_id)

        import tempfile

        from hyperqueue_tpu.worker.localcomm import LocalCommListener

        self.localcomm = LocalCommListener(self, Path(tempfile.gettempdir()))
        await self.localcomm.start()

        tasks = [
            asyncio.create_task(self._message_loop()),
            asyncio.create_task(self._send_drainer()),
            asyncio.create_task(self._heartbeat_loop()),
            asyncio.create_task(self._limits_loop()),
        ]
        # always started: the server can force overviews on at any time
        # while a dashboard listens (set_overview_override)
        tasks.append(asyncio.create_task(self._overview_loop()))
        stop_wait = asyncio.create_task(self._stop.wait())
        try:
            done, pending = await asyncio.wait(
                tasks + [stop_wait], return_when=asyncio.FIRST_COMPLETED
            )
            for t in done:
                if t is not stop_wait and t.exception():
                    raise t.exception()
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            if self.configuration.on_server_lost == "finish-running":
                logger.warning("server lost (%s); finishing running tasks", e)
                await self._finish_running_then_exit()
            else:
                logger.warning("server lost (%s); stopping", e)
        finally:
            for t in tasks + [stop_wait]:
                t.cancel()
            for rt in self.running.values():
                if rt.launched is not None:
                    rt.launched.kill()
            if self.localcomm is not None:
                self.localcomm.close()
            if self._conn:
                self._conn.close()

    async def _finish_running_then_exit(self) -> None:
        while self.running:
            await asyncio.sleep(0.1)

    async def _message_loop(self) -> None:
        while True:
            msg = await self._conn.recv()
            op = msg.get("op")
            if op == "compute":
                shared = msg.get("shared_bodies")
                for task_msg in msg["tasks"]:
                    if shared is not None and "b" in task_msg:
                        # resolve the shared/separate split; the body dict
                        # stays shared between tasks (read-only downstream)
                        task_msg["body"] = shared[task_msg.pop("b")]
                    self._try_start(task_msg)
            elif op == "cancel":
                for task_id in msg["task_ids"]:
                    self._cancel_task(task_id)
            elif op == "retract":
                for task_id, instance in msg["tasks"]:
                    # retract may only reclaim NOT-YET-STARTED tasks: remove
                    # from the blocked queue, never touch running ones (the
                    # server treats ok=False as "it started, leave it be").
                    # The instance is echoed so the server can discard stale
                    # answers, like every other task message.
                    before = self._n_blocked
                    self._remove_blocked(task_id)
                    await self._send(
                        {
                            "op": "retract_response",
                            "id": task_id,
                            "instance": instance,
                            "ok": self._n_blocked < before,
                        }
                    )
            elif op == "set_overview_override":
                interval = msg.get("interval")
                self._overview_override = (
                    float(interval) if interval is not None else None
                )
                self._overview_wake.set()
            elif op == "stop":
                self._stop.set()
                return
            else:
                logger.warning("unknown server message %r", op)

    def _park(self, sig: tuple, task_msg: dict) -> None:
        """Park a task in its signature group, ordered by priority
        (descending, stable): a later high-priority compute message must
        start before earlier low-priority backlog once resources free up —
        the server-side analog is the displacement retract (reference
        test_reactor.rs test_prefill_submit_high_priority)."""
        group = self.blocked.setdefault(sig, [])
        priority = tuple(task_msg.get("priority") or (0, 0))
        idx = len(group)
        while idx > 0 and tuple(group[idx - 1].get("priority") or (0, 0)) < priority:
            idx -= 1
        group.insert(idx, task_msg)
        self._n_blocked += 1

    def _try_start(self, task_msg: dict) -> bool:
        """Returns False if the task was parked in the blocked queue."""
        entries = task_msg.get("entries", [])
        sig = self._entries_sig(task_msg) if entries else ()
        if entries and sig in self.blocked:
            # peers with the same signature are already waiting: the head
            # could not allocate, so this one cannot either — park without
            # probing
            self._park(sig, task_msg)
            return False
        allocation = self.allocator.try_allocate(entries)
        if allocation is None and entries:
            logger.debug("task %d blocked on resources", task_msg["id"])
            self._park(sig, task_msg)
            return False
        self._start_with_allocation(task_msg, allocation)
        return True

    def _start_with_allocation(self, task_msg: dict, allocation) -> None:
        body = task_msg.get("body") or {}
        if (
            self.zero_worker
            and not body.get("stream")
            and not body.get("time_limit")
        ):
            # zero-worker fast path: no process ever exists, so completing
            # inline (two queued uplinks, immediate release) skips the
            # per-task coroutine + future + RunningTask entirely — the
            # worker-side floor of the <0.1 ms/task overhead target
            task_id = task_msg["id"]
            instance = task_msg.get("instance", 0)
            self._sendq.put_nowait(
                {"op": "task_running", "id": task_id, "instance": instance}
            )
            self._sendq.put_nowait(
                {"op": "task_finished", "id": task_id, "instance": instance}
            )
            self.last_task_time = time.monotonic()
            if allocation is not None:
                self.allocator.release(allocation)
                if self.blocked:
                    # re-probe parked tasks — but via call_soon: this fast
                    # path runs inside _retry_blocked itself, and a direct
                    # call would recurse one frame per blocked task
                    asyncio.get_running_loop().call_soon(self._retry_blocked)
            return
        future = asyncio.create_task(self._run_task(task_msg, allocation))
        self.running[task_msg["id"]] = RunningTask(
            task_msg, allocation, None, future
        )

    async def _run_task(self, task_msg: dict, allocation) -> None:
        task_id = task_msg["id"]
        instance = task_msg.get("instance", 0)
        held_stream_dir = None
        try:
            streamer = None
            body = task_msg.get("body") or {}
            stream_dir = body.get("stream")
            if stream_dir:
                # stream paths carry JOB-scope placeholders (reference
                # test_placeholders.py stream_submit_placeholder); task-
                # scope ones are a hard submit-time error
                # (cli._check_submit_placeholders) — a stream dir is
                # shared by the whole job
                import os as _os

                from hyperqueue_tpu.ids import task_id_job
                from hyperqueue_tpu.utils.placeholders import (
                    fill_placeholders,
                )

                stream_dir = fill_placeholders(stream_dir, {
                    "JOB_ID": str(task_id_job(task_id)),
                    "SUBMIT_DIR": body.get("submit_dir") or _os.getcwd(),
                    "SERVER_UID": self.server_uid,
                })
                streamer = self._acquire_streamer(stream_dir)
                held_stream_dir = stream_dir
            extra_env = {}
            if self.localcomm is not None:
                extra_env["HQ_LOCAL_SOCKET"] = self.localcomm.socket_path
                extra_env["HQ_TOKEN"] = self.localcomm.register_task(task_id)
            launched = await launch_task(
                task_msg,
                allocation,
                server_uid=self.server_uid,
                worker_id=self.worker_id,
                zero_worker=self.zero_worker,
                streamer=streamer,
                extra_env=extra_env,
            )
            rt = self.running.get(task_id)
            if rt is not None:
                rt.launched = launched
            await self._send(
                {"op": "task_running", "id": task_id, "instance": instance}
            )
            # per-task time limit (reference: task futures carry stop
            # reasons; program.rs timeout path): kill and fail on expiry
            time_limit = (task_msg.get("body") or {}).get("time_limit")
            timed_out = False
            if time_limit:
                try:
                    code, detail = await asyncio.wait_for(
                        launched.wait(), timeout=float(time_limit)
                    )
                except asyncio.TimeoutError:
                    timed_out = True
                    launched.kill()
                    await launched.wait()
                    code, detail = -1, ""
            else:
                code, detail = await launched.wait()
            if timed_out:
                if streamer is not None:
                    streamer.close_task(task_id, instance)
                await self._send(
                    {
                        "op": "task_failed",
                        "id": task_id,
                        "instance": instance,
                        "error": f"time limit of {time_limit}s exceeded",
                    }
                )
                return
            if streamer is not None:
                streamer.close_task(task_id, instance)
            if code == 0:
                await self._send(
                    {"op": "task_finished", "id": task_id, "instance": instance}
                )
            else:
                error = f"program exited with code {code}"
                if detail:
                    error += f"\nstderr (tail):\n{detail}"
                await self._send(
                    {
                        "op": "task_failed",
                        "id": task_id,
                        "instance": instance,
                        "error": error,
                    }
                )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 - report, don't kill the worker
            logger.exception("task %d launch failed", task_id)
            try:
                await self._send(
                    {
                        "op": "task_failed",
                        "id": task_id,
                        "instance": instance,
                        "error": f"failed to launch: {e}",
                    }
                )
            except (ConnectionError, OSError):
                pass
        finally:
            self.last_task_time = time.monotonic()
            if held_stream_dir is not None:
                self._release_streamer(held_stream_dir)
            if self.localcomm is not None:
                self.localcomm.unregister_task(task_id)
            rt = self.running.pop(task_id, None)
            if rt is not None and rt.allocation is not None:
                self.allocator.release(rt.allocation)
            self._retry_blocked()

    # keep this many stream writers' fds open at most; in-use writers are
    # never closed, so the bound can be exceeded while > MAX distinct
    # stream dirs have running tasks
    MAX_STREAM_WRITERS = 64

    def _acquire_streamer(self, stream_dir: str):
        """Get-or-open the StreamWriter for a stream dir and hold a
        refcount on it for a running task.

        Eviction closes only ZERO-refcount writers (closing one under a
        running task fails that task's next write_chunk/close_task), in
        least-recently-USED order: reused dirs move to the end of the
        dict, so insertion order is true LRU order.  Pair every call with
        _release_streamer."""
        streamer = self._streamers.get(stream_dir)
        if streamer is not None:
            self._streamers.pop(stream_dir)
            self._streamers[stream_dir] = streamer
        else:
            from hyperqueue_tpu.events.outputlog import StreamWriter

            # bound open fds: per-job stream dirs accumulate on a
            # long-lived worker.  If every writer is in use the bound is
            # exceeded rather than an in-flight task's writer closed.
            while len(self._streamers) >= self.MAX_STREAM_WRITERS:
                victim = next(
                    (
                        d for d in self._streamers
                        if not self._streamer_users.get(d)
                    ),
                    None,
                )
                if victim is None:
                    break
                self._streamers.pop(victim).close()
            streamer = StreamWriter(
                stream_dir, self.worker_id, self.server_uid
            )
            self._streamers[stream_dir] = streamer
        self._streamer_users[stream_dir] = (
            self._streamer_users.get(stream_dir, 0) + 1
        )
        return streamer

    def _release_streamer(self, stream_dir: str) -> None:
        remaining = self._streamer_users.get(stream_dir, 1) - 1
        if remaining > 0:
            self._streamer_users[stream_dir] = remaining
        else:
            self._streamer_users.pop(stream_dir, None)

    @staticmethod
    def _entries_sig(task_msg: dict):
        return tuple(
            (e["name"], e["amount"], e.get("policy", "compact"))
            for e in task_msg.get("entries", [])
        )

    def _retry_blocked(self) -> None:
        """Retry blocked tasks after a resource release.

        Blocked tasks are bucketed by resource signature; identical
        signatures fail identically, so each release only probes one head
        per signature group — O(#signatures), not O(#blocked), per release
        (the deep prefill queue made the naive scan the worker's dominant
        cost at 50k+ short tasks).  Signature groups are probed in
        head-priority order so a freed resource goes to the
        highest-priority waiter."""
        for sig in sorted(
            self.blocked,
            key=lambda s: tuple(self.blocked[s][0].get("priority") or (0, 0)),
            reverse=True,
        ):
            group = self.blocked.get(sig)
            while group:
                task_msg = group[0]
                allocation = self.allocator.try_allocate(
                    task_msg.get("entries", [])
                )
                if allocation is None:
                    break
                group.pop(0)
                self._n_blocked -= 1
                self._start_with_allocation(task_msg, allocation)
            if not group:
                self.blocked.pop(sig, None)

    def _remove_blocked(self, task_id: int) -> None:
        for sig, group in list(self.blocked.items()):
            kept = [t for t in group if t["id"] != task_id]
            self._n_blocked -= len(group) - len(kept)
            if kept:
                self.blocked[sig] = kept
            else:
                self.blocked.pop(sig, None)

    def _cancel_task(self, task_id: int) -> None:
        self._remove_blocked(task_id)
        rt = self.running.get(task_id)
        if rt is not None:
            if rt.launched is not None:
                rt.launched.kill()
            else:
                rt.future.cancel()

    async def _overview_loop(self) -> None:
        """Send hw telemetry on the configured cadence — or on the
        server-forced one while a dashboard listens (reference
        SetOverviewIntervalOverride, messages/worker.rs:76-165, applied in
        worker/rpc.rs:394-396)."""
        from hyperqueue_tpu.worker.hwmonitor import HwSampler

        sampler = HwSampler()
        while True:
            interval = (
                self._overview_override
                if self._overview_override is not None
                else self.configuration.overview_interval_secs
            )
            self._overview_wake.clear()
            if interval <= 0:
                # overviews disabled: park until an override arrives
                await self._overview_wake.wait()
                continue
            try:
                # an arriving override interrupts the wait so a dashboard
                # gets telemetry immediately even under a long configured
                # interval (and detach restores the old cadence at once)
                await asyncio.wait_for(
                    self._overview_wake.wait(), timeout=interval
                )
                continue  # re-read the effective interval
            except asyncio.TimeoutError:
                pass  # cadence elapsed: sample and send
            # sampling shells out to nvidia-smi/rocm-smi (blocking, up to
            # seconds on a wedged driver); keep it off the event loop so
            # heartbeats and task messaging never stall
            hw = await asyncio.to_thread(sampler.sample)
            await self._send(
                {
                    "op": "overview",
                    "hw": hw,
                    "n_running": len(self.running),
                }
            )

    async def _heartbeat_loop(self) -> None:
        interval = max(self.configuration.heartbeat_secs, 0.5)
        while True:
            await asyncio.sleep(interval)
            await self._send({"op": "heartbeat"})

    async def _goodbye(self, reason: str) -> None:
        """Tell the server this is a DELIBERATE exit (idle/time limit), so
        requeued tasks don't get charged a crash (reference CrashLimit:
        stops and time limits don't count). Sent directly — the batching
        drainer may never run again once _stop is set."""
        try:
            async with self._send_lock:
                await self._conn.send({"op": "goodbye", "reason": reason})
        except (ConnectionError, OSError):
            pass

    async def _limits_loop(self) -> None:
        while True:
            await asyncio.sleep(0.5)
            now = time.monotonic()
            limit = self.configuration.time_limit_secs
            if limit > 0 and now - self.started_at >= limit:
                logger.info("time limit reached; stopping")
                await self._goodbye("time limit")
                self._stop.set()
                return
            idle = self.configuration.idle_timeout_secs
            if (
                idle > 0
                and not self.running
                and not self.blocked
                and now - self.last_task_time >= idle
            ):
                logger.info("idle timeout reached; stopping")
                await self._goodbye("idle timeout")
                self._stop.set()
                return


async def run_worker(
    host: str,
    port: int,
    secret_key: bytes | None,
    configuration: WorkerConfiguration,
    zero_worker: bool = False,
) -> None:
    runtime = WorkerRuntime(
        host, port, secret_key, configuration, zero_worker=zero_worker
    )
    await runtime.run()
