"""Worker-side resource pools and allocator.

Reference: crates/tako/src/internal/worker/resources/{pool.rs,allocator.rs} —
pools hold concrete indices (non-fungible), possibly partitioned into NUMA
groups, or a fungible sum; allocations claim whole indices plus at most one
fractional share, and the claimed indices surface to tasks as
HQ_RESOURCE_VALUES_<name> env vars.

Policies (reference pool.rs:164-456):
  compact  — prefer few groups (best effort)
  compact! — must use the minimal possible number of groups
  tight    — prefer the groups that end up most fully used
  tight!   — strict version of tight
  scatter  — spread across groups round-robin
  all      — claim every free index of the resource
"""

from __future__ import annotations

from dataclasses import dataclass, field

from hyperqueue_tpu.resources.amount import FRACTIONS_PER_UNIT
from hyperqueue_tpu.resources.descriptor import (
    DescriptorKind,
    ResourceDescriptor,
)
from hyperqueue_tpu.resources.request import AllocationPolicy


@dataclass
class ResourceClaim:
    resource: str
    indices: list[str]                       # fully claimed indices
    fraction_index: str | None = None        # index claimed fractionally
    fraction: int = 0
    sum_amount: int = 0                      # for SUM pools

    def amount(self) -> int:
        return (
            len(self.indices) * FRACTIONS_PER_UNIT
            + self.fraction
            + self.sum_amount
        )

    def env_value(self) -> str:
        labels = list(self.indices)
        if self.fraction_index is not None:
            labels.append(self.fraction_index)
        return ",".join(labels)


@dataclass
class Allocation:
    claims: list[ResourceClaim] = field(default_factory=list)

    def claim_for(self, resource: str) -> ResourceClaim | None:
        for claim in self.claims:
            if claim.resource == resource:
                return claim
        return None


class _IndexPool:
    """Pool of concrete indices in groups; tracks full and fractional use."""

    def __init__(self, groups: list[list[str]]):
        self.groups = groups
        self.group_of: dict[str, int] = {}
        for gi, group in enumerate(groups):
            for label in group:
                self.group_of[label] = gi
        self.free: list[str] = [label for group in groups for label in group]
        # partially claimed: label -> remaining fraction (0..FRACTIONS)
        self.partial: dict[str, int] = {}

    def total_free(self) -> int:
        return len(self.free) * FRACTIONS_PER_UNIT + sum(self.partial.values())

    def _group_free_count(self) -> dict[int, int]:
        counts = {gi: 0 for gi in range(len(self.groups))}
        for label in self.free:
            counts[self.group_of[label]] += 1
        return counts

    def _ordered_free(
        self,
        policy: AllocationPolicy,
        n_units: int,
        preferred_groups: set[int] | None = None,
    ) -> list[str]:
        """Free indices ordered so the first n_units match the policy.

        preferred_groups (coupling, reference descriptor.rs:249-295 +
        worker groups.rs): groups already used by coupled resources of the
        same allocation sort first, so e.g. the claimed cpus land on the NUMA
        node of the claimed gpu.
        """
        counts = self._group_free_count()
        if preferred_groups:
            pref = lambda l: 0 if self.group_of[l] in preferred_groups else 1  # noqa: E731
        else:
            pref = lambda l: 0  # noqa: E731
        if policy in (AllocationPolicy.SCATTER,):
            # round-robin across groups
            by_group: dict[int, list[str]] = {}
            for label in self.free:
                by_group.setdefault(self.group_of[label], []).append(label)
            out: list[str] = []
            while any(by_group.values()):
                for gi in sorted(by_group):
                    if by_group[gi]:
                        out.append(by_group[gi].pop(0))
            return out
        if policy in (
            AllocationPolicy.TIGHT,
            AllocationPolicy.FORCE_TIGHT,
        ):
            # prefer groups with the FEWEST free indices (fill them up)
            return sorted(
                self.free,
                key=lambda l: (pref(l), counts[self.group_of[l]],
                               self.group_of[l], l),
            )
        # compact/default: prefer groups with the MOST free indices so the
        # allocation lands in as few groups as possible
        return sorted(
            self.free,
            key=lambda l: (pref(l), -counts[self.group_of[l]],
                           self.group_of[l], l),
        )

    def allocate(
        self,
        amount: int,
        policy: AllocationPolicy,
        preferred_groups: set[int] | None = None,
    ) -> ResourceClaim | None:
        if policy is AllocationPolicy.ALL:
            if self.partial or not self.free:
                return None
            claim = ResourceClaim(resource="", indices=list(self.free))
            self.free.clear()
            return claim
        units, fraction = divmod(amount, FRACTIONS_PER_UNIT)
        if self.total_free() < amount:
            return None
        if len(self.free) < units or (
            fraction
            and len(self.free) == units
            and not any(f >= fraction for f in self.partial.values())
        ):
            return None
        ordered = self._ordered_free(policy, units, preferred_groups)
        if policy in (AllocationPolicy.FORCE_COMPACT,):
            # all units must come from the minimal number of groups
            counts = self._group_free_count()
            need = units + (1 if fraction else 0)
            best = sorted(counts.values(), reverse=True)
            got, n_groups = 0, 0
            for c in best:
                if got >= need:
                    break
                got += c
                n_groups += 1
            # verify the ordered prefix uses exactly n_groups groups
            prefix = ordered[:need]
            if len({self.group_of[l] for l in prefix}) > max(n_groups, 1):
                return None
        taken = ordered[:units]
        claim = ResourceClaim(resource="", indices=taken)
        for label in taken:
            self.free.remove(label)
        if fraction:
            # prefer an already-partial index with enough remaining
            donor = None
            for label, remaining in sorted(self.partial.items()):
                if remaining >= fraction:
                    donor = label
                    break
            if donor is not None:
                self.partial[donor] -= fraction
                if self.partial[donor] == 0:
                    del self.partial[donor]
            else:
                # break a fresh free index (prefer same ordering)
                rest = [l for l in ordered[units:] if l in self.free]
                if not rest:
                    # roll back
                    self.free.extend(taken)
                    return None
                donor = rest[0]
                self.free.remove(donor)
                self.partial[donor] = FRACTIONS_PER_UNIT - fraction
            claim.fraction_index = donor
            claim.fraction = fraction
        return claim

    def release(self, claim: ResourceClaim) -> None:
        self.free.extend(claim.indices)
        if claim.fraction_index is not None:
            remaining = self.partial.get(claim.fraction_index, 0) + claim.fraction
            if remaining >= FRACTIONS_PER_UNIT:
                self.partial.pop(claim.fraction_index, None)
                self.free.append(claim.fraction_index)
            else:
                self.partial[claim.fraction_index] = remaining


class _SumPool:
    def __init__(self, size: int):
        self.free = size

    def total_free(self) -> int:
        return self.free

    def allocate(self, amount: int, policy: AllocationPolicy) -> ResourceClaim | None:
        if policy is AllocationPolicy.ALL:
            if self.free == 0:
                return None
            claim = ResourceClaim(resource="", indices=[], sum_amount=self.free)
            self.free = 0
            return claim
        if self.free < amount:
            return None
        self.free -= amount
        return ResourceClaim(resource="", indices=[], sum_amount=amount)

    def release(self, claim: ResourceClaim) -> None:
        self.free += claim.sum_amount


class ResourceAllocator:
    """All pools of one worker; try_allocate is all-or-nothing.

    Reference allocator.rs:215 (try_allocate) — on failure the request waits;
    the server should rarely over-assign because its dense view mirrors these
    pools, but races on fractional packing are possible and handled by
    queueing on the worker (worker/runtime.py blocked queue).
    """

    def __init__(self, descriptor: ResourceDescriptor):
        self.pools: dict[str, _IndexPool | _SumPool] = {}
        self.coupled: set[str] = set(
            descriptor.coupling.names if descriptor.coupling else ()
        )
        for item in descriptor.items:
            if item.kind is DescriptorKind.SUM:
                self.pools[item.name] = _SumPool(item.sum_size)
            else:
                self.pools[item.name] = _IndexPool(item.index_groups())

    def try_allocate(self, entries: list[dict]) -> Allocation | None:
        """entries: [{name, amount, policy}] from the compute message.

        Coupled resources (descriptor coupling) are allocated first and their
        groups steer later coupled claims onto the same groups — the
        lightweight equivalent of the reference's worker-side group MILP
        (reference worker/resources/groups.rs:19-61).
        """
        allocation = Allocation()
        used_groups: set[int] = set()
        # scarcest coupled resource first so it anchors the group choice
        def order_key(entry):
            if entry["name"] not in self.coupled:
                return (1, 0)
            pool = self.pools.get(entry["name"])
            return (0, pool.total_free() if pool else 0)

        for entry in sorted(entries, key=order_key):
            pool = self.pools.get(entry["name"])
            policy = AllocationPolicy.parse(entry.get("policy", "compact"))
            if pool is None:
                self._rollback(allocation)
                return None
            coupled = entry["name"] in self.coupled
            claim = pool.allocate(
                int(entry["amount"]),
                policy,
                preferred_groups=used_groups if coupled else None,
            ) if isinstance(pool, _IndexPool) else pool.allocate(
                int(entry["amount"]), policy
            )
            if claim is None:
                self._rollback(allocation)
                return None
            claim.resource = entry["name"]
            allocation.claims.append(claim)
            if coupled and isinstance(pool, _IndexPool):
                for label in claim.indices:
                    used_groups.add(pool.group_of[label])
                if claim.fraction_index is not None:
                    used_groups.add(pool.group_of[claim.fraction_index])
        return allocation

    def _rollback(self, allocation: Allocation) -> None:
        for claim in allocation.claims:
            self.pools[claim.resource].release(claim)

    def release(self, allocation: Allocation) -> None:
        self._rollback(allocation)
