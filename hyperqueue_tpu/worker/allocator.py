"""Worker-side resource pools and allocator.

Reference: crates/tako/src/internal/worker/resources/{pool.rs,allocator.rs} —
pools hold concrete indices (non-fungible), possibly partitioned into NUMA
groups, or a fungible sum; allocations claim whole indices plus at most one
fractional share, and the claimed indices surface to tasks as
HQ_RESOURCE_VALUES_<name> env vars.

Policies (reference pool.rs:164-456):
  compact  — prefer few groups (best effort)
  compact! — must use the minimal possible number of groups
  tight    — prefer the groups that end up most fully used
  tight!   — strict version of tight
  scatter  — spread across groups round-robin
  all      — claim every free index of the resource
"""

from __future__ import annotations

from dataclasses import dataclass, field

from hyperqueue_tpu.resources.amount import FRACTIONS_PER_UNIT
from hyperqueue_tpu.resources.descriptor import (
    DescriptorKind,
    ResourceDescriptor,
)
from hyperqueue_tpu.resources.request import AllocationPolicy

# Policies whose group choice participates in the joint group solve
# (reference request.rs:64 is_relevant_for_coupling: scatter and all are not).
_COUPLING_POLICIES = frozenset(
    {
        AllocationPolicy.COMPACT,
        AllocationPolicy.FORCE_COMPACT,
        AllocationPolicy.TIGHT,
        AllocationPolicy.FORCE_TIGHT,
    }
)
_FORCED_POLICIES = frozenset(
    {AllocationPolicy.FORCE_COMPACT, AllocationPolicy.FORCE_TIGHT}
)

# Keep the exhaustive subset enumeration bounded (reference caps the fast
# path at FAST_MAX_GROUPS=8 groups and 3 coupled resources, pool.rs:57-58).
_MAX_SOLVER_GROUPS = 12


def group_solver(
    states: list[list[tuple[int, int]]],
    requests: list[tuple[int, int]],
    weights: list[tuple[int, int, int, int, float]],
) -> tuple[list[list[int]], float] | None:
    """Exact NUMA group selection: which groups each coupled resource draws
    from, maximizing the reference's MILP objective via depth-first
    branch-and-bound (the reference solves the identical model with an LP
    solver, worker/resources/groups.rs:19-61).

    states[i]   per group j of resource i: (whole_free_units f_ij,
                max_partial_fraction g_ij)
    requests[i] (whole_units r_i, fraction z_i) requested of resource i
    weights     (i1, j1, i2, j2, w): affinity bonus if group j1 of resource
                i1 AND group j2 of resource i2 are both selected

    Objective per selected group (groups.rs:59-62): -1024 tax per group (so
    group count is minimized first), minus f/32 for whole-unit requests
    (prefer emptier-tail groups), plus g/(U/16) when the group holds a
    partial index large enough to donate the fractional part; plus the
    coupling weights of co-selected pairs.

    Returns (selected group indices per resource, objective) or None if
    infeasible / too large for exact search.
    """
    n = len(states)
    subsets: list[list[tuple[float, int]]] = []  # per resource: (value, mask)
    for state, (units, fraction) in zip(states, requests):
        n_groups = len(state)
        if n_groups > _MAX_SOLVER_GROUPS:
            return None
        vals = []
        for f, g in state:
            if fraction == 0:
                vals.append(-1024.0 - f / 32.0)
            elif g >= fraction:
                vals.append(-1024.0 + g / (FRACTIONS_PER_UNIT / 16.0))
            else:
                vals.append(-1024.0)
        feasible: list[tuple[float, int]] = []
        for mask in range(1, 1 << n_groups):
            whole = 0
            eff = 0  # whole units + donor bonus (groups.rs:105-112)
            value = 0.0
            for j in range(n_groups):
                if mask >> j & 1:
                    f, g = state[j]
                    whole += f
                    eff += f + (1 if fraction and g >= fraction else 0)
                    value += vals[j]
            if fraction == 0:
                ok = whole >= units
            else:
                ok = eff >= units + 1 and whole >= units
            if ok:
                feasible.append((value, mask))
        if not feasible:
            return None
        # best value first; ties broken toward lower group indices
        feasible.sort(key=lambda t: (-t[0], t[1]))
        subsets.append(feasible)

    # bound: best subset value for the remaining resources plus every weight
    # that could still apply
    best_tail = [0.0] * (n + 1)
    for i in range(n - 1, -1, -1):
        best_tail[i] = best_tail[i + 1] + subsets[i][0][0]
    weight_by_hi = [0.0] * n  # weights whose higher resource index is i
    for i1, _j1, i2, _j2, w in weights:
        if w > 0:
            weight_by_hi[max(i1, i2)] += w
    weight_tail = [0.0] * (n + 1)
    for i in range(n - 1, -1, -1):
        weight_tail[i] = weight_tail[i + 1] + weight_by_hi[i]

    best_obj = -float("inf")
    best_masks: list[int] | None = None
    chosen = [0] * n

    def dfs(i: int, acc: float) -> None:
        nonlocal best_obj, best_masks
        if i == n:
            if acc > best_obj:
                best_obj = acc
                best_masks = chosen[:]
            return
        if acc + best_tail[i] + weight_tail[i] <= best_obj:
            return
        for value, mask in subsets[i]:
            bonus = 0.0
            for i1, j1, i2, j2, w in weights:
                hi, lo = (i1, i2) if i1 > i2 else (i2, i1)
                hj, lj = (j1, j2) if i1 > i2 else (j2, j1)
                if hi == i and lo <= i:
                    sel_lo = mask if lo == i else chosen[lo]
                    sel_hi = mask
                    if sel_hi >> hj & 1 and sel_lo >> lj & 1:
                        bonus += w
            chosen[i] = mask
            dfs(i + 1, acc + value + bonus)
        chosen[i] = 0

    dfs(0, 0.0)
    if best_masks is None:
        return None
    return (
        [
            [j for j in range(len(states[i])) if best_masks[i] >> j & 1]
            for i in range(n)
        ],
        best_obj,
    )


@dataclass
class ResourceClaim:
    resource: str
    indices: list[str]                       # fully claimed indices
    fraction_index: str | None = None        # index claimed fractionally
    fraction: int = 0
    sum_amount: int = 0                      # for SUM pools

    def amount(self) -> int:
        return (
            len(self.indices) * FRACTIONS_PER_UNIT
            + self.fraction
            + self.sum_amount
        )

    def env_value(self) -> str:
        labels = list(self.indices)
        if self.fraction_index is not None:
            labels.append(self.fraction_index)
        return ",".join(labels)


@dataclass
class Allocation:
    claims: list[ResourceClaim] = field(default_factory=list)

    def claim_for(self, resource: str) -> ResourceClaim | None:
        for claim in self.claims:
            if claim.resource == resource:
                return claim
        return None


class _IndexPool:
    """Pool of concrete indices in groups; tracks full and fractional use."""

    def __init__(self, groups: list[list[str]]):
        self.groups = groups
        self.group_of: dict[str, int] = {}
        for gi, group in enumerate(groups):
            for label in group:
                self.group_of[label] = gi
        self.free: list[str] = [label for group in groups for label in group]
        # partially claimed: label -> remaining fraction (0..FRACTIONS)
        self.partial: dict[str, int] = {}

    def total_free(self) -> int:
        return len(self.free) * FRACTIONS_PER_UNIT + sum(self.partial.values())

    def capacity(self) -> int:
        return sum(len(g) for g in self.groups) * FRACTIONS_PER_UNIT

    def group_free_state(self) -> list[tuple[int, int]]:
        """(whole_free_units, max_partial_fraction) per group — the f/g
        columns of the group solver (reference concise.rs amount_max_per_group)."""
        whole = [0] * len(self.groups)
        for label in self.free:
            whole[self.group_of[label]] += 1
        frac = [0] * len(self.groups)
        for label, remaining in self.partial.items():
            gi = self.group_of[label]
            if remaining > frac[gi]:
                frac[gi] = remaining
        return list(zip(whole, frac))

    def group_full_state(self) -> list[tuple[int, int]]:
        """Group state of a completely empty worker (for the forced-policy
        optimality baseline, reference allocator.rs:152-166)."""
        return [(len(g), 0) for g in self.groups]

    def _group_free_count(self) -> dict[int, int]:
        counts = {gi: 0 for gi in range(len(self.groups))}
        for label in self.free:
            counts[self.group_of[label]] += 1
        return counts

    def _ordered_free(
        self,
        policy: AllocationPolicy,
        n_units: int,
        preferred_groups: set[int] | None = None,
    ) -> list[str]:
        """Free indices ordered so the first n_units match the policy.

        preferred_groups (coupling, reference descriptor.rs:249-295 +
        worker groups.rs): groups already used by coupled resources of the
        same allocation sort first, so e.g. the claimed cpus land on the NUMA
        node of the claimed gpu.
        """
        counts = self._group_free_count()
        if preferred_groups:
            pref = lambda l: 0 if self.group_of[l] in preferred_groups else 1  # noqa: E731
        else:
            pref = lambda l: 0  # noqa: E731
        if policy in (AllocationPolicy.SCATTER,):
            # round-robin across groups
            by_group: dict[int, list[str]] = {}
            for label in self.free:
                by_group.setdefault(self.group_of[label], []).append(label)
            out: list[str] = []
            while any(by_group.values()):
                for gi in sorted(by_group):
                    if by_group[gi]:
                        out.append(by_group[gi].pop(0))
            return out
        if policy in (
            AllocationPolicy.TIGHT,
            AllocationPolicy.FORCE_TIGHT,
        ):
            # prefer groups with the FEWEST free indices (fill them up)
            return sorted(
                self.free,
                key=lambda l: (pref(l), counts[self.group_of[l]],
                               self.group_of[l], l),
            )
        # compact/default: when some group can hold the whole request,
        # BEST-FIT — the tightest such group first, preserving large holes
        # for future big requests (reference test_pool_compact1: two 3-cpu
        # tasks share one socket while an untouched one stays whole).
        # When no group fits, span as few groups as possible by taking
        # the fullest-free groups first.
        fits = any(c >= n_units for c in counts.values())

        def group_key(gi: int) -> tuple:
            c = counts[gi]
            if fits:
                # groups that fit, tightest first; too-small groups last
                return (0, c) if c >= n_units else (1, -c)
            return (0, -c)

        return sorted(
            self.free,
            key=lambda l: (pref(l), group_key(self.group_of[l]),
                           self.group_of[l], l),
        )

    def allocate(
        self,
        amount: int,
        policy: AllocationPolicy,
        preferred_groups: set[int] | None = None,
        group_mask: set[int] | None = None,
    ) -> ResourceClaim | None:
        """group_mask: restrict the claim to these groups — the group solver
        already made the group decision (reference pool.rs
        claim_resources_with_group_mask)."""
        if policy is AllocationPolicy.ALL:
            if self.partial or not self.free:
                return None
            claim = ResourceClaim(resource="", indices=list(self.free))
            self.free.clear()
            return claim
        units, fraction = divmod(amount, FRACTIONS_PER_UNIT)
        if group_mask is not None:
            in_mask = lambda l: self.group_of[l] in group_mask  # noqa: E731
        else:
            in_mask = lambda l: True  # noqa: E731
        has_partial_donor = bool(fraction) and any(
            f >= fraction for l, f in self.partial.items() if in_mask(l)
        )
        need = units + (1 if fraction and not has_partial_donor else 0)
        if sum(1 for l in self.free if in_mask(l)) < need:
            return None
        # best-fit group choice must count the fresh index a fractional
        # remainder will break (`need`, not `units`) — otherwise a 2.5-unit
        # request picks a 2-free group and splits the donor into another
        ordered = [
            l
            for l in self._ordered_free(policy, need, preferred_groups)
            if in_mask(l)
        ]
        if group_mask is None and policy is AllocationPolicy.FORCE_COMPACT:
            # all units must come from the minimal number of groups (the
            # masked path skips this: the group solver already enforced it)
            counts = self._group_free_count()
            fc_need = units + (1 if fraction else 0)
            best = sorted(counts.values(), reverse=True)
            got, n_groups = 0, 0
            for c in best:
                if got >= fc_need:
                    break
                got += c
                n_groups += 1
            # verify the ordered prefix uses exactly n_groups groups
            prefix = ordered[:fc_need]
            if len({self.group_of[l] for l in prefix}) > max(n_groups, 1):
                return None
        taken = ordered[:units]
        claim = ResourceClaim(resource="", indices=taken)
        for label in taken:
            self.free.remove(label)
        if fraction:
            # prefer an already-partial index with enough remaining, in a
            # group the whole units already use (compactness)
            taken_groups = {self.group_of[l] for l in taken}
            donor = None
            for label, remaining in sorted(
                self.partial.items(),
                key=lambda kv: (self.group_of[kv[0]] not in taken_groups,
                                kv[0]),
            ):
                if in_mask(label) and remaining >= fraction:
                    donor = label
                    break
            if donor is not None:
                self.partial[donor] -= fraction
                if self.partial[donor] == 0:
                    del self.partial[donor]
            else:
                # break a fresh free index (prefer same ordering)
                rest = [l for l in ordered[units:] if l in self.free]
                if not rest:
                    # roll back
                    self.free.extend(taken)
                    return None
                donor = rest[0]
                self.free.remove(donor)
                self.partial[donor] = FRACTIONS_PER_UNIT - fraction
            claim.fraction_index = donor
            claim.fraction = fraction
        return claim

    def release(self, claim: ResourceClaim) -> None:
        self.free.extend(claim.indices)
        if claim.fraction_index is not None:
            remaining = self.partial.get(claim.fraction_index, 0) + claim.fraction
            if remaining >= FRACTIONS_PER_UNIT:
                self.partial.pop(claim.fraction_index, None)
                self.free.append(claim.fraction_index)
            else:
                self.partial[claim.fraction_index] = remaining


class _SumPool:
    def capacity(self) -> int:
        return self.size

    def __init__(self, size: int):
        self.size = size
        self.free = size

    def total_free(self) -> int:
        return self.free

    def allocate(self, amount: int, policy: AllocationPolicy) -> ResourceClaim | None:
        if policy is AllocationPolicy.ALL:
            if self.free == 0:
                return None
            claim = ResourceClaim(resource="", indices=[], sum_amount=self.free)
            self.free = 0
            return claim
        if self.free < amount:
            return None
        self.free -= amount
        return ResourceClaim(resource="", indices=[], sum_amount=amount)

    def release(self, claim: ResourceClaim) -> None:
        self.free += claim.sum_amount


class ResourceAllocator:
    """All pools of one worker; try_allocate is all-or-nothing.

    Reference allocator.rs:215 (try_allocate) — on failure the request waits;
    the server should rarely over-assign because its dense view mirrors these
    pools, but races on fractional packing are possible and handled by
    queueing on the worker (worker/runtime.py blocked queue).
    """

    def __init__(self, descriptor: ResourceDescriptor):
        self.pools: dict[str, _IndexPool | _SumPool] = {}
        for item in descriptor.items:
            if item.kind is DescriptorKind.SUM:
                self.pools[item.name] = _SumPool(item.sum_size)
            else:
                self.pools[item.name] = _IndexPool(item.index_groups())
        n_groups_of = {
            name: len(pool.groups)
            for name, pool in self.pools.items()
            if isinstance(pool, _IndexPool)
        }
        self.coupling_weights = (
            descriptor.coupling.expand_weights(n_groups_of)
            if descriptor.coupling
            else []
        )
        # forced-policy optimality baseline: objective achievable on an
        # EMPTY worker, cached per request shape (reference allocator.rs
        # optional_objectives)
        self._optimal_cache: dict[tuple, float] = {}
        # memoized group solves keyed on (request shape, pool free-state
        # fingerprint): the exact subset enumeration is up to 2^12-1 subsets
        # per coupled resource and re-runs on every blocked-queue retry of a
        # saturated worker, where the free state usually hasn't changed
        self._solve_cache: dict[
            tuple, tuple[list[list[int]], float] | None
        ] = {}

    def _solve_groups(
        self, coupled: list[tuple[dict, "_IndexPool"]], empty: bool
    ) -> tuple[list[list[int]], float] | None:
        states = []
        requests = []
        index_of = {entry["name"]: i for i, (entry, _) in enumerate(coupled)}
        for entry, pool in coupled:
            states.append(
                pool.group_full_state() if empty else pool.group_free_state()
            )
            requests.append(divmod(int(entry["amount"]), FRACTIONS_PER_UNIT))
        # (request shape, free-state fingerprint) fully determines the solve
        # (weights are fixed per worker); memoize so blocked-queue retries on
        # an unchanged worker skip the exponential enumeration
        key = (
            tuple((e["name"], int(e["amount"])) for e, _ in coupled),
            tuple(tuple(s) for s in states),
            empty,
        )
        if key in self._solve_cache:
            return self._solve_cache[key]
        weights = [
            (
                index_of[w.resource1],
                w.group1,
                index_of[w.resource2],
                w.group2,
                float(w.weight),
            )
            for w in self.coupling_weights
            if w.resource1 in index_of and w.resource2 in index_of
        ]
        solved = group_solver(states, requests, weights)
        if len(self._solve_cache) >= 1024:
            self._solve_cache.pop(next(iter(self._solve_cache)))
        self._solve_cache[key] = solved
        return solved

    def try_allocate(self, entries: list[dict]) -> Allocation | None:
        """entries: [{name, amount, policy}] from the compute message.

        Multi-group (NUMA) resources with coupling-relevant policies are
        group-decided JOINTLY by the exact group solver — minimal group
        count, maximal coupling weight — and then claimed within the chosen
        group masks (reference allocator.rs:115-205 has_resources_for_request
        + claim_resources). Forced policies additionally require the solve to
        be as good as on an empty worker, else the task waits."""
        coupled: list[tuple[dict, _IndexPool]] = []
        any_forced = False
        plan = []  # (entry, pool, policy): parse once, reuse in the claims
        for entry in entries:
            pool = self.pools.get(entry["name"])
            if pool is None:
                return None
            policy = AllocationPolicy.parse(entry.get("policy", "compact"))
            # cheap infeasibility gate — failed attempts dominate on
            # saturated workers (every release retries the blocked queue).
            # ALL ignores the amount and takes the ENTIRE pool, which must
            # be untouched (reference test_allocator.rs:260-280: after one
            # cpu is taken an `all` request fails; the scheduler kernel's
            # free == total check mirrors this).
            if policy is AllocationPolicy.ALL:
                if pool.total_free() < pool.capacity():
                    return None
            elif pool.total_free() < int(entry["amount"]):
                return None
            plan.append((entry, pool, policy))
            if (
                isinstance(pool, _IndexPool)
                and 1 < len(pool.groups) <= _MAX_SOLVER_GROUPS
                and policy in _COUPLING_POLICIES
            ):
                coupled.append((entry, pool))
                any_forced = any_forced or policy in _FORCED_POLICIES
        # run the solver only when it can change the outcome: a forced
        # policy needs the optimality check, or coupling weights tie at
        # least two of the requested resources together; plain compact/tight
        # without weights is served by the cheap per-pool ordering (the
        # solver's per-group objective agrees with it)
        if coupled:
            names = {e["name"] for e, _ in coupled}
            weights_apply = any(
                w.resource1 in names and w.resource2 in names
                for w in self.coupling_weights
            )
            if not any_forced and not weights_apply:
                coupled = []

        masks: dict[str, set[int]] = {}
        if coupled:
            solved = self._solve_groups(coupled, empty=False)
            if solved is None:
                # genuinely infeasible right now (pools over the size guard
                # were never admitted into `coupled`)
                if any_forced:
                    return None
                # non-forced: fall through, unmasked claims will fail cleanly
            else:
                groups_sel, objective = solved
                if any_forced:
                    key = tuple(
                        (e["name"], int(e["amount"])) for e, _ in coupled
                    )
                    optimal = self._optimal_cache.get(key)
                    if optimal is None:
                        empty_solved = self._solve_groups(coupled, empty=True)
                        if empty_solved is None:
                            return None
                        optimal = empty_solved[1] - 0.1
                        if len(self._optimal_cache) >= 1024:
                            self._optimal_cache.pop(
                                next(iter(self._optimal_cache))
                            )
                        self._optimal_cache[key] = optimal
                    if objective < optimal:
                        return None  # a better-shaped moment will come
                for (entry, _pool), sel in zip(coupled, groups_sel):
                    masks[entry["name"]] = set(sel)

        allocation = Allocation()
        for entry, pool, policy in plan:
            if isinstance(pool, _IndexPool):
                claim = pool.allocate(
                    int(entry["amount"]),
                    policy,
                    group_mask=masks.get(entry["name"]),
                )
            else:
                claim = pool.allocate(int(entry["amount"]), policy)
            if claim is None:
                self._rollback(allocation)
                return None
            claim.resource = entry["name"]
            allocation.claims.append(claim)
        return allocation

    def _rollback(self, allocation: Allocation) -> None:
        for claim in allocation.claims:
            self.pools[claim.resource].release(claim)

    def release(self, allocation: Allocation) -> None:
        self._rollback(allocation)
