"""Batch-manager detection on the worker host.

Reference: crates/hyperqueue/src/common/manager/{pbs,slurm,info,common}.rs —
detect PBS/Slurm from the environment (PBS_JOBID / SLURM_JOB_ID), look up the
remaining walltime (qstat / scontrol) so the worker can set its own time
limit, and expose the manager + job id to the server.
"""

from __future__ import annotations

import os
import re
import subprocess
from dataclasses import dataclass


@dataclass
class ManagerInfo:
    manager: str  # "pbs" | "slurm" | "none"
    job_id: str = ""
    remaining_secs: float = 0.0  # 0 = unknown


def _parse_walltime(text: str) -> float:
    """'HH:MM:SS' or 'D-HH:MM:SS' -> seconds."""
    days = 0
    if "-" in text:
        d, text = text.split("-", 1)
        days = int(d)
    parts = [int(p) for p in text.split(":")]
    while len(parts) < 3:
        parts.insert(0, 0)
    h, m, s = parts[-3:]
    return days * 86400 + h * 3600 + m * 60 + s


def _pbs_remaining(job_id: str) -> float:
    try:
        out = subprocess.run(
            ["qstat", "-f", job_id],
            capture_output=True,
            text=True,
            timeout=10,
        ).stdout
    except (OSError, subprocess.TimeoutExpired):
        return 0.0
    walltime = used = None
    for line in out.splitlines():
        line = line.strip()
        if line.startswith("Resource_List.walltime"):
            walltime = _parse_walltime(line.split("=", 1)[1].strip())
        elif line.startswith("resources_used.walltime"):
            used = _parse_walltime(line.split("=", 1)[1].strip())
    if walltime is None:
        return 0.0
    return max(walltime - (used or 0.0), 0.0)


def _slurm_remaining(job_id: str) -> float:
    try:
        out = subprocess.run(
            ["scontrol", "show", "job", job_id],
            capture_output=True,
            text=True,
            timeout=10,
        ).stdout
    except (OSError, subprocess.TimeoutExpired):
        return 0.0
    m = re.search(r"TimeLeft=(\S+)", out)
    if m and m.group(1) not in ("UNLIMITED", "NOT_SET"):
        return _parse_walltime(m.group(1))
    # older scontrol prints RunTime/TimeLimit instead of TimeLeft
    # (reference common/manager/slurm.rs parse_slurm_duration)
    limit = re.search(r"TimeLimit=(\S+)", out)
    run = re.search(r"RunTime=(\S+)", out)
    if limit and limit.group(1) not in ("UNLIMITED", "NOT_SET"):
        used = _parse_walltime(run.group(1)) if run else 0.0
        return max(_parse_walltime(limit.group(1)) - used, 0.0)
    return 0.0


def detect_manager(mode: str = "auto") -> ManagerInfo:
    """mode: auto | pbs | slurm | none."""
    if mode == "none":
        return ManagerInfo(manager="none")
    pbs_id = os.environ.get("PBS_JOBID", "")
    slurm_id = os.environ.get("SLURM_JOB_ID", "")
    if mode in ("auto", "pbs") and pbs_id:
        return ManagerInfo(
            manager="pbs", job_id=pbs_id, remaining_secs=_pbs_remaining(pbs_id)
        )
    if mode in ("auto", "slurm") and slurm_id:
        return ManagerInfo(
            manager="slurm",
            job_id=slurm_id,
            remaining_secs=_slurm_remaining(slurm_id),
        )
    if mode in ("pbs", "slurm"):
        raise RuntimeError(f"--manager {mode} requested but not detected in env")
    return ManagerInfo(manager="none")
