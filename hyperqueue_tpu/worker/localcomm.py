"""Node-local plane: `hq task notify` from inside a running task.

Reference: crates/tako/src/internal/worker/{localcomm,notifications}.rs — the
worker listens on a Unix socket; each task gets a random token in its env
(HQ_LOCAL_SOCKET / HQ_TOKEN); a notify message authenticated by the token is
forwarded to the server, which emits a task-notify event to listening
clients.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import secrets
from pathlib import Path

logger = logging.getLogger("hq.worker.localcomm")


class LocalCommListener:
    def __init__(self, runtime, work_dir: Path):
        self.runtime = runtime
        self.socket_path = str(
            Path(work_dir) / f"hq-local-{os.getpid()}.sock"
        )
        self.tokens: dict[str, int] = {}  # token -> packed task id
        self._server: asyncio.base_events.Server | None = None

    def register_task(self, task_id: int) -> str:
        token = secrets.token_hex(16)
        self.tokens[token] = task_id
        return token

    def unregister_task(self, task_id: int) -> None:
        self.tokens = {t: tid for t, tid in self.tokens.items() if tid != task_id}

    async def start(self) -> None:
        self._server = await asyncio.start_unix_server(
            self._handle, path=self.socket_path
        )

    def close(self) -> None:
        if self._server is not None:
            self._server.close()
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass

    async def _handle(self, reader, writer) -> None:
        try:
            data = await asyncio.wait_for(reader.readline(), timeout=5)
            msg = json.loads(data)
            token = msg.get("token", "")
            task_id = self.tokens.get(token)
            if task_id is None:
                writer.write(b'{"error": "invalid token"}\n')
            else:
                await self.runtime._send(
                    {
                        "op": "task_notify",
                        "id": task_id,
                        "payload": str(msg.get("payload", ""))[:4096],
                    }
                )
                writer.write(b'{"ok": true}\n')
            await writer.drain()
        except (asyncio.TimeoutError, json.JSONDecodeError, OSError) as e:
            logger.debug("local notify failed: %s", e)
        finally:
            writer.close()


def notify_from_task(payload: str) -> None:
    """Called by `hq task notify` INSIDE a task (sync, uses task env)."""
    import socket

    sock_path = os.environ.get("HQ_LOCAL_SOCKET")
    token = os.environ.get("HQ_TOKEN")
    if not sock_path or not token:
        raise RuntimeError(
            "not inside a hyperqueue task (HQ_LOCAL_SOCKET/HQ_TOKEN missing)"
        )
    with socket.socket(socket.AF_UNIX) as s:
        s.settimeout(5)
        s.connect(sock_path)
        s.sendall(
            (json.dumps({"token": token, "payload": payload}) + "\n").encode()
        )
        response = s.recv(4096)
    if b'"ok"' not in response:
        raise RuntimeError(f"notify rejected: {response.decode(errors='replace')}")
