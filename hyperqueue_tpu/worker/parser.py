"""Resource definition parser for `hq worker start --resource`.

Reference: crates/hyperqueue/src/worker/parser.rs (718 LoC) — syntaxes:
  name=range(1-5)          indices 1..5
  name=[a,b,c]             explicit list
  name=[[a,b],[c,d]]       groups (NUMA)
  name=sum(1024)           fungible amount (units)
  name=4 / name=4x2        shorthand: N indices / N groups x M
"""

from __future__ import annotations

import re

from hyperqueue_tpu.resources.amount import amount_from_str
from hyperqueue_tpu.resources.descriptor import ResourceDescriptorItem


class ResourceParseError(ValueError):
    pass


def parse_resource_definition(spec: str) -> ResourceDescriptorItem:
    name, sep, value = spec.partition("=")
    name = name.strip()
    value = value.strip()
    if not sep or not name or not value:
        raise ResourceParseError(
            f"invalid resource definition {spec!r}, expected name=value"
        )

    m = re.fullmatch(r"range\((\d+)-(\d+)\)", value)
    if m:
        lo, hi = int(m.group(1)), int(m.group(2))
        if hi < lo:
            raise ResourceParseError(f"empty range in {spec!r}")
        return ResourceDescriptorItem.range(name, lo, hi)

    m = re.fullmatch(r"sum\(([\d.]+)\)", value)
    if m:
        return ResourceDescriptorItem.sum(name, amount_from_str(m.group(1)))

    if value.startswith("[["):
        groups = _parse_nested_list(value, spec)
        return ResourceDescriptorItem.group_list(name, groups)

    if value.startswith("["):
        if not value.endswith("]"):
            raise ResourceParseError(f"unterminated list in {spec!r}")
        items = [v.strip() for v in value[1:-1].split(",") if v.strip()]
        if not items:
            raise ResourceParseError(f"empty list in {spec!r}")
        return ResourceDescriptorItem.list(name, items)

    m = re.fullmatch(r"(\d+)x(\d+)", value)
    if m:
        n_groups, per_group = int(m.group(1)), int(m.group(2))
        groups = [
            [str(g * per_group + i) for i in range(per_group)]
            for g in range(n_groups)
        ]
        return ResourceDescriptorItem.group_list(name, groups)

    if value.isdigit():
        return ResourceDescriptorItem.range(name, 0, int(value) - 1)

    raise ResourceParseError(f"cannot parse resource definition {spec!r}")


def _parse_nested_list(value: str, spec: str) -> list[list[str]]:
    if not value.endswith("]]"):
        raise ResourceParseError(f"unterminated group list in {spec!r}")
    inner = value[1:-1].strip()
    groups: list[list[str]] = []
    depth = 0
    current = ""
    for ch in inner:
        if ch == "[":
            depth += 1
            current = ""
        elif ch == "]":
            depth -= 1
            items = [v.strip() for v in current.split(",") if v.strip()]
            if items:
                groups.append(items)
        elif depth > 0:
            current += ch
    if not groups:
        raise ResourceParseError(f"empty group list in {spec!r}")
    return groups


def parse_resource_coupling(text: str):
    """Parse a --coupling value into a ResourceDescriptorCoupling.

    Two forms (reference parser.rs:229 parse_resource_coupling):
      "cpus,gpus"                        — plain names: same-index groups of
                                           the listed resources couple at the
                                           default weight 256
      "cpus[0]:gpus[0]=256,cpus[1]:gpus[1]" — explicit weighted group pairs
                                           (weight defaults to 256)
    """
    from hyperqueue_tpu.resources.descriptor import (
        CouplingWeight,
        ResourceDescriptorCoupling,
    )

    text = text.strip()
    if "[" not in text:
        return ResourceDescriptorCoupling(
            names=tuple(n.strip() for n in text.split(",") if n.strip())
        )
    item_re = re.compile(
        r"^\s*(\w+)\[(\d+)\]\s*:\s*(\w+)\[(\d+)\]\s*(?:=\s*(\d+))?\s*$"
    )
    weights = []
    for part in text.split(","):
        m = item_re.match(part)
        if m is None:
            raise ResourceParseError(f"invalid coupling item {part.strip()!r}")
        r1, g1, r2, g2, w = m.groups()
        weights.append(
            CouplingWeight(
                r1, int(g1), r2, int(g2), int(w) if w else 256
            ).normalized()
        )
    return ResourceDescriptorCoupling(weights=tuple(weights))
