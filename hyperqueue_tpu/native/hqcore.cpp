// hqcore: native hot-path structures for the hyperqueue_tpu server.
//
// The reference implements its whole runtime in Rust; the equivalent hot
// structures here are C++ behind a C ABI consumed via ctypes
// (hyperqueue_tpu/utils/native.py). Currently:
//
//   * TaskQueue — per-request-class ready queue: priority-bucketed FIFO of
//     packed u64 task ids with tombstone removal (mirrors
//     hyperqueue_tpu/scheduler/queues.py, itself mirroring reference
//     crates/tako/src/internal/scheduler/taskqueue.rs). At 1M ready tasks the
//     queue operations (add/priority_sizes/take) bound the host side of the
//     scheduling tick, which is why they get the native treatment first.
//
// Build: make -C hyperqueue_tpu/native   (produces libhqcore.so)

#include <cstdint>
#include <deque>
#include <map>
#include <unordered_set>
#include <utility>
#include <vector>

namespace {

using Priority = std::pair<int64_t, int64_t>;  // compared lexicographically

struct TaskQueue {
    // descending priority: std::map with reverse comparator
    std::map<Priority, std::deque<uint64_t>, std::greater<Priority>> levels;
    std::unordered_set<uint64_t> tombstones;
    int64_t size = 0;

    void compact_level(std::deque<uint64_t>& level) {
        if (tombstones.empty()) return;
        std::deque<uint64_t> kept;
        for (uint64_t id : level) {
            auto it = tombstones.find(id);
            if (it != tombstones.end()) {
                tombstones.erase(it);
            } else {
                kept.push_back(id);
            }
        }
        level.swap(kept);
    }
};

}  // namespace

extern "C" {

void* hq_queue_new() { return new TaskQueue(); }

void hq_queue_free(void* handle) { delete static_cast<TaskQueue*>(handle); }

void hq_queue_add(void* handle, int64_t prio_user, int64_t prio_sched,
                  uint64_t task_id) {
    auto* q = static_cast<TaskQueue*>(handle);
    q->levels[{prio_user, prio_sched}].push_back(task_id);
    q->size += 1;
}

void hq_queue_remove(void* handle, uint64_t task_id) {
    auto* q = static_cast<TaskQueue*>(handle);
    q->tombstones.insert(task_id);
    q->size -= 1;
}

int64_t hq_queue_len(void* handle) {
    return static_cast<TaskQueue*>(handle)->size;
}

// Fill up to max_levels (priority_user, priority_sched, count) triples in
// descending priority order, compacting tombstones on the way. Returns the
// number of levels written.
int64_t hq_queue_priority_sizes(void* handle, int64_t* out_prio_user,
                                int64_t* out_prio_sched, int64_t* out_counts,
                                int64_t max_levels) {
    auto* q = static_cast<TaskQueue*>(handle);
    int64_t n = 0;
    for (auto it = q->levels.begin(); it != q->levels.end();) {
        q->compact_level(it->second);
        if (it->second.empty()) {
            it = q->levels.erase(it);
            continue;
        }
        if (n < max_levels) {
            out_prio_user[n] = it->first.first;
            out_prio_sched[n] = it->first.second;
            out_counts[n] = static_cast<int64_t>(it->second.size());
            ++n;
        }
        ++it;
    }
    return n;
}

// Pop up to `count` ids at the given priority level (FIFO). Returns the
// number written to out_ids.
int64_t hq_queue_take(void* handle, int64_t prio_user, int64_t prio_sched,
                      int64_t count, uint64_t* out_ids) {
    auto* q = static_cast<TaskQueue*>(handle);
    auto it = q->levels.find({prio_user, prio_sched});
    if (it == q->levels.end()) return 0;
    q->compact_level(it->second);
    int64_t n = 0;
    while (!it->second.empty() && n < count) {
        out_ids[n++] = it->second.front();
        it->second.pop_front();
    }
    q->size -= n;
    if (it->second.empty()) q->levels.erase(it);
    return n;
}

// Batched mapping take: for each nonzero solver cell i, pop cell_count[i]
// ids from the queue of batch cell_batch[i] at that batch's priority and
// append them to out_ids; out_cell_n[i] records how many were written for
// the cell. One C call replaces thousands of per-cell ctypes round-trips in
// the tick's counts->assignments mapping. Returns total ids written.
int64_t hq_map_take(void** queue_handles, const int64_t* prio_user,
                    const int64_t* prio_sched, const int64_t* cell_batch,
                    const int64_t* cell_count, int64_t n_cells,
                    uint64_t* out_ids, int64_t* out_cell_n) {
    int64_t total = 0;
    for (int64_t i = 0; i < n_cells; ++i) {
        int64_t b = cell_batch[i];
        int64_t got = hq_queue_take(queue_handles[b], prio_user[b],
                                    prio_sched[b], cell_count[i],
                                    out_ids + total);
        out_cell_n[i] = got;
        total += got;
    }
    return total;
}

// Drain every id (descending priority, FIFO within level) into out_ids
// (caller sizes it via hq_queue_len). Used for debug dumps/restore.
int64_t hq_queue_all(void* handle, uint64_t* out_ids, int64_t max) {
    auto* q = static_cast<TaskQueue*>(handle);
    int64_t n = 0;
    for (auto& [prio, level] : q->levels) {
        q->compact_level(level);
        for (uint64_t id : level) {
            if (n >= max) return n;
            out_ids[n++] = id;
        }
    }
    return n;
}

}  // extern "C"
