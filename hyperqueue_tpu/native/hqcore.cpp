// hqcore: native hot-path structures for the hyperqueue_tpu server.
//
// The reference implements its whole runtime in Rust; the equivalent hot
// structures here are C++ behind a C ABI consumed via ctypes
// (hyperqueue_tpu/utils/native.py). Currently:
//
//   * TaskQueue — per-request-class ready queue: priority-bucketed FIFO of
//     packed u64 task ids with tombstone removal (mirrors
//     hyperqueue_tpu/scheduler/queues.py, itself mirroring reference
//     crates/tako/src/internal/scheduler/taskqueue.rs). At 1M ready tasks the
//     queue operations (add/priority_sizes/take) bound the host side of the
//     scheduling tick, which is why they get the native treatment first.
//
// Build: make -C hyperqueue_tpu/native   (produces libhqcore.so)

#include <cstdint>
#include <deque>
#include <map>
#include <unordered_set>
#include <utility>
#include <vector>

namespace {

using Priority = std::pair<int64_t, int64_t>;  // compared lexicographically

struct TaskQueue {
    // descending priority: std::map with reverse comparator
    std::map<Priority, std::deque<uint64_t>, std::greater<Priority>> levels;
    std::unordered_set<uint64_t> tombstones;
    int64_t size = 0;

    void compact_level(std::deque<uint64_t>& level) {
        if (tombstones.empty()) return;
        std::deque<uint64_t> kept;
        for (uint64_t id : level) {
            auto it = tombstones.find(id);
            if (it != tombstones.end()) {
                tombstones.erase(it);
            } else {
                kept.push_back(id);
            }
        }
        level.swap(kept);
    }
};

}  // namespace

extern "C" {

void* hq_queue_new() { return new TaskQueue(); }

void hq_queue_free(void* handle) { delete static_cast<TaskQueue*>(handle); }

void hq_queue_add(void* handle, int64_t prio_user, int64_t prio_sched,
                  uint64_t task_id) {
    auto* q = static_cast<TaskQueue*>(handle);
    q->levels[{prio_user, prio_sched}].push_back(task_id);
    q->size += 1;
}

void hq_queue_remove(void* handle, uint64_t task_id) {
    auto* q = static_cast<TaskQueue*>(handle);
    q->tombstones.insert(task_id);
    q->size -= 1;
}

int64_t hq_queue_len(void* handle) {
    return static_cast<TaskQueue*>(handle)->size;
}

// Fill up to max_levels (priority_user, priority_sched, count) triples in
// descending priority order, compacting tombstones on the way. Returns the
// number of levels written.
int64_t hq_queue_priority_sizes(void* handle, int64_t* out_prio_user,
                                int64_t* out_prio_sched, int64_t* out_counts,
                                int64_t max_levels) {
    auto* q = static_cast<TaskQueue*>(handle);
    int64_t n = 0;
    for (auto it = q->levels.begin(); it != q->levels.end();) {
        q->compact_level(it->second);
        if (it->second.empty()) {
            it = q->levels.erase(it);
            continue;
        }
        if (n < max_levels) {
            out_prio_user[n] = it->first.first;
            out_prio_sched[n] = it->first.second;
            out_counts[n] = static_cast<int64_t>(it->second.size());
            ++n;
        }
        ++it;
    }
    return n;
}

// Pop up to `count` ids at the given priority level (FIFO). Returns the
// number written to out_ids.
int64_t hq_queue_take(void* handle, int64_t prio_user, int64_t prio_sched,
                      int64_t count, uint64_t* out_ids) {
    auto* q = static_cast<TaskQueue*>(handle);
    auto it = q->levels.find({prio_user, prio_sched});
    if (it == q->levels.end()) return 0;
    q->compact_level(it->second);
    int64_t n = 0;
    while (!it->second.empty() && n < count) {
        out_ids[n++] = it->second.front();
        it->second.pop_front();
    }
    q->size -= n;
    if (it->second.empty()) q->levels.erase(it);
    return n;
}

// Drain every id (descending priority, FIFO within level) into out_ids
// (caller sizes it via hq_queue_len). Used for debug dumps/restore.
int64_t hq_queue_all(void* handle, uint64_t* out_ids, int64_t max) {
    auto* q = static_cast<TaskQueue*>(handle);
    int64_t n = 0;
    for (auto& [prio, level] : q->levels) {
        q->compact_level(level);
        for (uint64_t id : level) {
            if (n >= max) return n;
            out_ids[n++] = id;
        }
    }
    return n;
}

}  // extern "C"
