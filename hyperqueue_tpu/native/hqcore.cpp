// hqcore: native hot-path structures for the hyperqueue_tpu server.
//
// The reference implements its whole runtime in Rust; the equivalent hot
// structures here are C++ behind a C ABI consumed via ctypes
// (hyperqueue_tpu/utils/native.py). Currently:
//
//   * TaskQueue — per-request-class ready queue: priority-bucketed FIFO of
//     packed u64 task ids with tombstone removal (mirrors
//     hyperqueue_tpu/scheduler/queues.py, itself mirroring reference
//     crates/tako/src/internal/scheduler/taskqueue.rs). At 1M ready tasks the
//     queue operations (add/priority_sizes/take) bound the host side of the
//     scheduling tick, which is why they get the native treatment first.
//
// Build: make -C hyperqueue_tpu/native   (produces libhqcore.so)

#include <cstdint>
#include <deque>
#include <map>
#include <unordered_set>
#include <utility>
#include <vector>

namespace {

using Priority = std::pair<int64_t, int64_t>;  // compared lexicographically

struct TaskQueue {
    // descending priority: std::map with reverse comparator
    std::map<Priority, std::deque<uint64_t>, std::greater<Priority>> levels;
    std::unordered_set<uint64_t> tombstones;
    int64_t size = 0;

    void compact_level(std::deque<uint64_t>& level) {
        if (tombstones.empty()) return;
        std::deque<uint64_t> kept;
        for (uint64_t id : level) {
            auto it = tombstones.find(id);
            if (it != tombstones.end()) {
                tombstones.erase(it);
            } else {
                kept.push_back(id);
            }
        }
        level.swap(kept);
    }
};

}  // namespace

extern "C" {

void* hq_queue_new() { return new TaskQueue(); }

void hq_queue_free(void* handle) { delete static_cast<TaskQueue*>(handle); }

void hq_queue_add(void* handle, int64_t prio_user, int64_t prio_sched,
                  uint64_t task_id) {
    auto* q = static_cast<TaskQueue*>(handle);
    q->levels[{prio_user, prio_sched}].push_back(task_id);
    q->size += 1;
}

void hq_queue_remove(void* handle, uint64_t task_id) {
    auto* q = static_cast<TaskQueue*>(handle);
    q->tombstones.insert(task_id);
    q->size -= 1;
}

int64_t hq_queue_len(void* handle) {
    return static_cast<TaskQueue*>(handle)->size;
}

// Fill up to max_levels (priority_user, priority_sched, count) triples in
// descending priority order, compacting tombstones on the way. Returns the
// number of levels written.
int64_t hq_queue_priority_sizes(void* handle, int64_t* out_prio_user,
                                int64_t* out_prio_sched, int64_t* out_counts,
                                int64_t max_levels) {
    auto* q = static_cast<TaskQueue*>(handle);
    int64_t n = 0;
    for (auto it = q->levels.begin(); it != q->levels.end();) {
        q->compact_level(it->second);
        if (it->second.empty()) {
            it = q->levels.erase(it);
            continue;
        }
        if (n < max_levels) {
            out_prio_user[n] = it->first.first;
            out_prio_sched[n] = it->first.second;
            out_counts[n] = static_cast<int64_t>(it->second.size());
            ++n;
        }
        ++it;
    }
    return n;
}

// Pop up to `count` ids at the given priority level (FIFO). Returns the
// number written to out_ids.
int64_t hq_queue_take(void* handle, int64_t prio_user, int64_t prio_sched,
                      int64_t count, uint64_t* out_ids) {
    auto* q = static_cast<TaskQueue*>(handle);
    auto it = q->levels.find({prio_user, prio_sched});
    if (it == q->levels.end()) return 0;
    q->compact_level(it->second);
    int64_t n = 0;
    while (!it->second.empty() && n < count) {
        out_ids[n++] = it->second.front();
        it->second.pop_front();
    }
    q->size -= n;
    if (it->second.empty()) q->levels.erase(it);
    return n;
}

// Batched mapping take: for each nonzero solver cell i, pop cell_count[i]
// ids from the queue of batch cell_batch[i] at that batch's priority and
// append them to out_ids; out_cell_n[i] records how many were written for
// the cell. One C call replaces thousands of per-cell ctypes round-trips in
// the tick's counts->assignments mapping. Returns total ids written.
int64_t hq_map_take(void** queue_handles, const int64_t* prio_user,
                    const int64_t* prio_sched, const int64_t* cell_batch,
                    const int64_t* cell_count, int64_t n_cells,
                    uint64_t* out_ids, int64_t* out_cell_n) {
    int64_t total = 0;
    for (int64_t i = 0; i < n_cells; ++i) {
        int64_t b = cell_batch[i];
        int64_t got = hq_queue_take(queue_handles[b], prio_user[b],
                                    prio_sched[b], cell_count[i],
                                    out_ids + total);
        out_cell_n[i] = got;
        total += got;
    }
    return total;
}

// Drain every id (descending priority, FIFO within level) into out_ids
// (caller sizes it via hq_queue_len). Used for debug dumps/restore.
int64_t hq_queue_all(void* handle, uint64_t* out_ids, int64_t max) {
    auto* q = static_cast<TaskQueue*>(handle);
    int64_t n = 0;
    for (auto& [prio, level] : q->levels) {
        q->compact_level(level);
        for (uint64_t id : level) {
            if (n >= max) return n;
            out_ids[n++] = id;
        }
    }
    return n;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// hq_cut_scan: the host-side dense tick solve (the numpy fallback's exact
// semantics — ops/assign.greedy_cut_scan_numpy — as one native pass).
//
// Priority-ordered batches water-fill over workers in (visit-class asc,
// worker-index asc) order; variants in user preference order share one
// `remaining`; ALL-policy resources require an untouched pool and drain it
// whole. Two early exits numpy cannot express cheaply: the scan stops when
// every task slot is gone, and a per-resource free-maximum upper bound
// skips variants no worker can fit anymore (after the cluster saturates,
// hundreds of tail batches cost O(R) instead of O(W x R) each).
// ---------------------------------------------------------------------------

extern "C" void hq_cut_scan(
    const int64_t* free_in,   // (W,R) row-major
    const int64_t* total,     // (W,R) or nullptr (no ALL requests)
    const int64_t* nt_in,     // (W)
    const int32_t* lifetime,  // (W)
    const int64_t* needs,     // (B,V,R)
    const int32_t* all_mask,  // (B,V,R) or nullptr
    const int64_t* sizes,     // (B)
    const int32_t* min_time,  // (B,V)
    const int32_t* class_m,   // (M,W) visit class per mask row per worker
    const int32_t* order_ids, // (B,V) mask row per batch/variant
    int64_t W, int64_t R, int64_t B, int64_t V, int64_t M,
    int32_t* counts)          // (B,V,W) out, caller-zeroed
{
    std::vector<int64_t> free(free_in, free_in + W * R);
    std::vector<int64_t> nt(nt_in, nt_in + W);
    int64_t nt_total = 0;
    for (int64_t w = 0; w < W; ++w) nt_total += nt[w] > 0 ? nt[w] : 0;

    // per-resource upper bound of the column max (only ever decreases;
    // tightened to the exact max whenever a scan touches the column)
    std::vector<int64_t> ub_max(R, 0);
    for (int64_t w = 0; w < W; ++w)
        for (int64_t r = 0; r < R; ++r)
            if (free[w * R + r] > ub_max[r]) ub_max[r] = free[w * R + r];

    // per mask row: workers in (class asc, index asc) order via counting
    // sort (classes < 16 — ops/assign.N_VISIT_CLASSES)
    std::vector<std::vector<int32_t>> visit(M);
    {
        std::vector<std::vector<int32_t>> buckets(16);
        for (int64_t m = 0; m < M; ++m) {
            for (auto& b : buckets) b.clear();
            for (int64_t w = 0; w < W; ++w) {
                int32_t c = class_m[m * W + w];
                if (c < 0) c = 0;
                if (c > 15) c = 15;
                buckets[c].push_back(static_cast<int32_t>(w));
            }
            auto& ord = visit[m];
            ord.reserve(W);
            for (auto& b : buckets) ord.insert(ord.end(), b.begin(), b.end());
        }
    }

    for (int64_t b = 0; b < B; ++b) {
        int64_t remaining = sizes[b];
        if (remaining <= 0) continue;
        if (nt_total <= 0) break;  // no task slots anywhere: nothing more
        for (int64_t v = 0; v < V && remaining > 0; ++v) {
            const int64_t* need = needs + (b * V + v) * R;
            const int32_t* am =
                all_mask ? all_mask + (b * V + v) * R : nullptr;
            bool any_req = false, feasible = true;
            for (int64_t r = 0; r < R; ++r) {
                bool is_all = am && am[r] > 0;
                if (need[r] > 0 || is_all) {
                    any_req = true;
                    if (!is_all && need[r] > ub_max[r]) {
                        feasible = false;  // no worker can fit this anymore
                        break;
                    }
                }
            }
            if (!any_req || !feasible) continue;
            int32_t mt = min_time[b * V + v];
            const auto& ord = visit[order_ids[b * V + v]];
            for (int32_t w : ord) {
                if (remaining <= 0) break;
                if (nt[w] <= 0 || mt > lifetime[w]) continue;
                int64_t cap = INT64_MAX;
                const int64_t* fw = &free[static_cast<int64_t>(w) * R];
                for (int64_t r = 0; r < R; ++r) {
                    bool is_all = am && am[r] > 0;
                    if (is_all) {
                        const int64_t tw = total[static_cast<int64_t>(w) * R + r];
                        int64_t c = (tw > 0 && fw[r] == tw) ? 1 : 0;
                        if (c < cap) cap = c;
                    } else if (need[r] > 0) {
                        int64_t c = fw[r] / need[r];
                        if (c < cap) cap = c;
                    }
                    if (cap == 0) break;
                }
                if (cap <= 0) continue;
                if (cap > nt[w]) cap = nt[w];
                if (cap > remaining) cap = remaining;
                // assign `cap` tasks of (b, v) to worker w
                counts[(b * V + v) * W + w] = static_cast<int32_t>(cap);
                int64_t* fwm = &free[static_cast<int64_t>(w) * R];
                for (int64_t r = 0; r < R; ++r) {
                    bool is_all = am && am[r] > 0;
                    if (is_all) {
                        fwm[r] = 0;
                    } else if (need[r] > 0) {
                        fwm[r] -= cap * need[r];
                    }
                }
                nt[w] -= cap;
                nt_total -= cap;
                remaining -= cap;
            }
            // tighten the column bounds for the resources this variant
            // consumed (exact recompute, amortized over the skips it buys)
            for (int64_t r = 0; r < R; ++r) {
                if (need[r] > 0 || (am && am[r] > 0)) {
                    int64_t mx = 0;
                    for (int64_t w2 = 0; w2 < W; ++w2) {
                        const int64_t f = free[w2 * R + r];
                        if (f > mx) mx = f;
                    }
                    ub_max[r] = mx;
                }
            }
        }
    }
}

// Nonzero cells of a (B,V,W) int32 counts array in row-major order —
// replaces np.nonzero in the tick's mapping phase (~1.5 ms at 256x2x1024).
// Returns the number of cells written; out arrays must hold at least
// min(n, capacity) entries.
extern "C" int64_t hq_nonzero(
    const int32_t* counts, int64_t n,
    int64_t* bs_vs_ws,   // flattened flat-index per cell
    int64_t* vals,
    int64_t capacity
) {
    int64_t out = 0;
    for (int64_t i = 0; i < n; ++i) {
        int32_t c = counts[i];
        if (c != 0) {
            if (out >= capacity) return out;
            bs_vs_ws[out] = i;
            vals[out] = c;
            ++out;
        }
    }
    return out;
}
