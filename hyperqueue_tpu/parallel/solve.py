"""Multi-chip sharded tick solver.

Scaling model: the dense tick state is (W, R) with W = workers — the axis that
grows with cluster size (reference target: 1k workers, BASELINE.json 1M x 1k).
We shard W across a jax.sharding.Mesh axis "w" with shard_map; batches/needs
are replicated (they are tiny: B x V x R ints).

Semantics: IDENTICAL to the single-chip kernel (ops/assign.greedy_cut_scan),
by construction. Both water-fill each (batch, variant) over workers in
(visit-class ascending, global worker index ascending) order, where the visit
classes come from the same host_visit_classes precomputation. shard_map splits
the worker axis contiguously, so "global worker index order" within a class is
exactly (device ascending, local index ascending) — the sharded body computes
each local worker's global water-fill prefix as

    prefix(w) = capacity of strictly-lower classes (cluster-wide)
              + capacity of w's class on lower-index devices
              + exclusive local cumsum within w's class

All three terms come from ONE all_gather of the per-device (C,)-vector of
per-class capacity sums per variant step (C = N_VISIT_CLASSES = 16) — pure ICI
traffic, no host round-trip, no resharding of the (W, R) state. Exactness is
pinned by tests/test_parallel.py, which asserts bitwise count equality with
the single-chip kernel on random and adversarial instances.

Memory layout note: the per-batch visit-class one-hots (B, V, W, C) are
expanded INSIDE the shard_map body from the worker-sharded class table
(class_m is sharded (M, W/D) per device), so no replicated (B, V, W, C)
tensor is ever materialized — each device builds only its own
(B, V, W/D, C) slice. An earlier revision expanded the one-hots outside the
shard_map, which materialized the full W axis on every device (268 MB at
B=256, W=8192) and dominated the sharded solve's cost.

Reference anchor: the solver IS the production scheduler there too
(crates/tako/src/internal/scheduler/{main.rs:40-46,solver.rs:16-461}); this
module is its multi-device form, selected with `--scheduler=multichip`
(models/multichip.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax moved shard_map out of experimental (and renamed check_rep ->
# check_vma) across the versions this repo must run on; resolve once here
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
    _SHARD_MAP_KW = {"check_vma": False}
else:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

    _SHARD_MAP_KW = {"check_rep": False}

from hyperqueue_tpu.ops.assign import (
    _water_fill_classed,
    expand_onehots,
    scan_batches,
)


def make_worker_mesh(n_devices: int | None = None) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(devices, axis_names=("w",))


def _sharded_water_fill_classed(cap, remaining, class_onehot, axis):
    """Classed water-fill with a cluster-wide prefix.

    cap (Wl,), class_onehot (Wl, C): LOCAL worker shards. Returns
    (assign (Wl,), assigned_total int32 replicated). The fill itself IS
    ops.assign._water_fill_classed — this wrapper only gathers the per-class
    capacity sums across devices (the single collective) and feeds them in
    as the global totals + lower-device same-class offsets, so the sharded
    fill reduces to the single-chip one by construction.
    """
    my_dev = jax.lax.axis_index(axis)
    per_class_local = jnp.sum(cap[:, None] * class_onehot, axis=0)  # (C,)
    all_per_class = jax.lax.all_gather(per_class_local, axis)  # (D, C)
    per_class_global = jnp.sum(all_per_class, axis=0)  # (C,)
    n_dev = all_per_class.shape[0]
    lower_dev = jnp.sum(
        jnp.where(
            (jnp.arange(n_dev) < my_dev)[:, None], all_per_class, 0
        ),
        axis=0,
    )  # (C,) same-class capacity on lower-index devices
    return _water_fill_classed(
        cap, remaining, class_onehot,
        per_class_total=per_class_global,
        same_class_before=lower_dev,
    )


def _sharded_gang_select(elig, group_onehot, n, axis):
    """Collective form of ops.assign._gang_select_local: elig/group_onehot
    are LOCAL worker shards; the per-group eligible counts are gathered
    across devices (one (G,)-vector all_gather), the chosen group is a
    replicated argmax, and each local take-prefix is shifted by the chosen
    group's eligible count on lower-index devices — shard_map splits the
    worker axis contiguously, so this reproduces the single-chip "first n
    eligible members in global index order" selection exactly."""
    my_dev = jax.lax.axis_index(axis)
    per_group_local = jnp.sum(elig[:, None] * group_onehot, axis=0)  # (G,)
    all_per_group = jax.lax.all_gather(per_group_local, axis)  # (D, G)
    per_group = jnp.sum(all_per_group, axis=0)  # (G,)
    feasible = per_group >= n
    any_feas = jnp.any(feasible)
    chosen = jnp.where(
        any_feas, jnp.argmax(feasible), jnp.argmax(per_group)
    )
    chosen_oh = (
        jnp.arange(group_onehot.shape[1], dtype=jnp.int32) == chosen
    )
    col = jnp.sum(group_onehot * chosen_oh[None, :].astype(jnp.int32),
                  axis=1)
    sel = elig * col
    n_dev = all_per_group.shape[0]
    # sum(sel) on a device IS its per_group_local[chosen]
    lower = jnp.sum(
        jnp.where((jnp.arange(n_dev) < my_dev)[:, None], all_per_group, 0)
        * chosen_oh[None, :].astype(jnp.int32)
    )
    prefix = jnp.cumsum(sel) - sel + lower
    take = sel * (prefix < n).astype(jnp.int32)
    return take, any_feas


def _sharded_body(
    free, nt_free, lifetime, needs, sizes, min_time, class_m, order_ids,
    total=None, all_mask=None,
    gang_nodes=None, gang_ok=None, group_onehot=None, policy_mask=None,
):
    """shard_map body: free/nt_free/lifetime/class_m/total are local worker
    shards; needs/sizes/min_time/order_ids/all_mask are replicated. The
    scan itself is ops.assign.scan_batches — the SAME code the single-chip
    kernel runs — with only the water-fill swapped for the
    cluster-wide-prefix variant, so single/multi-chip parity is structural.

    The one-hot expansion happens here, per device, over the LOCAL worker
    slice: class_m arrives as this device's (M, Wl) shard, so the expanded
    (B, V, Wl, C) tensor is 1/D of the full volume (the SAME
    ops.assign.expand_onehots the single-chip kernel uses, barrier
    included).
    """
    onehots = expand_onehots(class_m, order_ids)

    def water_fill(cap, remaining, class_onehot):
        return _sharded_water_fill_classed(cap, remaining, class_onehot, "w")

    def gang_select(elig, goh, n):
        return _sharded_gang_select(elig, goh, n, "w")

    return scan_batches(
        free, nt_free, lifetime, needs, sizes, min_time, onehots, water_fill,
        total=total, all_mask=all_mask,
        gang_nodes=gang_nodes, gang_ok=gang_ok, group_onehot=group_onehot,
        gang_select=gang_select if gang_nodes is not None else None,
        policy_mask=policy_mask,
    )


def _sharded_cut_scan_impl(
    mesh: Mesh, free, nt_free, lifetime, needs, sizes, min_time, class_m,
    order_ids, total=None, all_mask=None,
    gang_nodes=None, gang_ok=None, group_onehot=None, policy_mask=None,
):
    in_specs = [
        P("w", None),              # free
        P("w"),                    # nt_free
        P("w"),                    # lifetime
        P(),                       # needs
        P(),                       # sizes
        P(),                       # min_time
        P(None, "w"),              # class_m (per-mask class table, W-sharded)
        P(),                       # order_ids
    ]
    args = [free, nt_free, lifetime, needs, sizes, min_time, class_m,
            order_ids]
    # optional ALL-policy/gang inputs: None args are dropped from the pytree
    # so the no-ALL/no-gang compiled program is unchanged
    if total is not None:
        in_specs.append(P("w", None))
        args.append(total)
    if all_mask is not None:
        in_specs.append(P())
        args.append(all_mask)
    if gang_nodes is not None:
        in_specs.extend([P(), P("w"), P("w", None)])
        args.extend([gang_nodes, gang_ok, group_onehot])
    if policy_mask is not None:
        in_specs.append(P(None, "w"))  # (B, W) per-batch worker mask
        args.append(policy_mask)

    def body(free, nt_free, lifetime, needs, sizes, min_time, class_m,
             order_ids, *extra):
        i = 0
        t = m = gn = go = goh = pm = None
        if total is not None:
            t = extra[i]
            i += 1
        if all_mask is not None:
            m = extra[i]
            i += 1
        if gang_nodes is not None:
            gn, go, goh = extra[i:i + 3]
            i += 3
        if policy_mask is not None:
            pm = extra[i]
        return _sharded_body(
            free, nt_free, lifetime, needs, sizes, min_time, class_m,
            order_ids, total=t, all_mask=m,
            gang_nodes=gn, gang_ok=go, group_onehot=goh, policy_mask=pm,
        )

    return _shard_map(
        body,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(P(None, None, "w"), P("w", None), P("w")),
        **_SHARD_MAP_KW,
    )(*args)


@functools.partial(jax.jit, static_argnames=("mesh",))
def sharded_cut_scan(
    mesh: Mesh, free, nt_free, lifetime, needs, sizes, min_time, class_m,
    order_ids, total=None, all_mask=None,
    gang_nodes=None, gang_ok=None, group_onehot=None, policy_mask=None,
):
    """Worker-sharded variant of ops.assign.greedy_cut_scan — same inputs,
    same outputs, identical semantics.

    free/total (W, R), nt_free/lifetime/gang_ok (W,), class_m (M, W),
    policy_mask (B, W) and group_onehot (W, G) sharded on axis "w";
    needs/sizes/min_time/order_ids/all_mask/gang_nodes replicated. Returns
    counts (B, V, W) sharded on W, plus free/nt_free after.
    """
    return _sharded_cut_scan_impl(
        mesh, free, nt_free, lifetime, needs, sizes, min_time, class_m,
        order_ids, total=total, all_mask=all_mask,
        gang_nodes=gang_nodes, gang_ok=gang_ok, group_onehot=group_onehot,
        policy_mask=policy_mask,
    )


@functools.partial(
    jax.jit, static_argnames=("mesh",), donate_argnums=(1, 2)
)
def sharded_cut_scan_donate(
    mesh: Mesh, free, nt_free, lifetime, needs, sizes, min_time, class_m,
    order_ids, total=None, all_mask=None,
    gang_nodes=None, gang_ok=None, group_onehot=None, policy_mask=None,
):
    """`sharded_cut_scan` with `free`/`nt_free` DONATED: the input buffers
    are consumed and their storage reused for `free_after`/`nt_after`.

    This is the device-resident tick's solve (parallel/resident.py): solve
    N's outputs become solve N+1's inputs without ever crossing the host
    boundary, so the per-tick host->device traffic is only the dirty-row
    delta. Callers MUST not touch the passed free/nt_free arrays again.
    """
    return _sharded_cut_scan_impl(
        mesh, free, nt_free, lifetime, needs, sizes, min_time, class_m,
        order_ids, total=total, all_mask=all_mask,
        gang_nodes=gang_nodes, gang_ok=gang_ok, group_onehot=group_onehot,
        policy_mask=policy_mask,
    )


@functools.lru_cache(maxsize=4)
def _mesh_shardings(mesh: Mesh):
    """NamedSharding objects per mesh, built once: the production tick
    places tensors every solve, and re-constructing shardings per call is
    avoidable host work on the hot path.

    Returns (w2, w1, rep, cm): (W, R)-sharded, (W,)-sharded, replicated,
    and the (M, W) class-table sharding."""
    return (
        NamedSharding(mesh, P("w", None)),
        NamedSharding(mesh, P("w")),
        NamedSharding(mesh, P()),
        NamedSharding(mesh, P(None, "w")),
    )


def place_tick_inputs(mesh: Mesh, free, nt_free, lifetime, needs, sizes,
                      min_time, class_m, order_ids, total=None,
                      all_mask=None, gang_nodes=None, gang_ok=None,
                      group_onehot=None, policy_mask=None):
    """Device-put the tick tensors with the proper shardings."""
    w2, w1, rep, cm = _mesh_shardings(mesh)
    out = (
        jax.device_put(free, w2),
        jax.device_put(nt_free, w1),
        jax.device_put(lifetime, w1),
        jax.device_put(needs, rep),
        jax.device_put(sizes, rep),
        jax.device_put(min_time, rep),
        jax.device_put(class_m, cm),
        jax.device_put(order_ids, rep),
    )
    has_gang = gang_nodes is not None
    has_pmask = policy_mask is not None
    if total is not None or all_mask is not None or has_gang or has_pmask:
        out = out + (
            None if total is None else jax.device_put(total, w2),
            None if all_mask is None else jax.device_put(all_mask, rep),
        )
    if has_gang:
        out = out + (
            jax.device_put(gang_nodes, rep),
            jax.device_put(gang_ok, w1),
            jax.device_put(group_onehot, w2),
        )
    elif has_pmask:
        out = out + (None, None, None)
    if has_pmask:
        out = out + (jax.device_put(policy_mask, cm),)
    return out
