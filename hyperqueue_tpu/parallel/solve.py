"""Multi-chip sharded tick solver.

Scaling model: the dense tick state is (W, R) with W = workers — the axis that
grows with cluster size (reference target: 1k workers, BASELINE.json 1M x 1k).
We shard W across a jax.sharding.Mesh axis "w" with shard_map; batches/needs
are replicated (they are tiny: B x V x R ints).

The only cross-device dependency in the cut-scan is the water-fill's global
prefix: "how much of this batch was absorbed by workers on earlier devices".
That is one all_gather of per-device capacity sums (D scalars) per variant
step — pure ICI traffic, no host round-trip, no resharding of the (W, R)
state. Worker preference order becomes device-major (device 0's workers
first, scarcity-aware within a device), which is a valid deterministic
preference order of the same family the single-chip kernel uses.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hyperqueue_tpu.ops.assign import _variant_capacity, _water_fill

_WASTE_Q = 65536


def make_worker_mesh(n_devices: int | None = None) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(devices, axis_names=("w",))


def _sharded_body(free, nt_free, lifetime, needs, sizes, min_time, scarcity):
    """shard_map body: free/nt_free/lifetime are local worker shards."""
    axis = "w"
    my_dev = jax.lax.axis_index(axis)
    n_dev = jax.lax.axis_size(axis)
    n_variants = needs.shape[1]

    def batch_body(carry, batch):
        free, nt_free, = carry
        b_needs, b_size, b_min_time = batch
        remaining_global = b_size
        counts_v = []
        for v in range(n_variants):
            need = b_needs[v]
            time_ok = b_min_time[v] <= lifetime
            cap = _variant_capacity(free, nt_free, need, time_ok)
            cap = jnp.minimum(cap, remaining_global)
            local_sum = jnp.sum(cap)
            # global exclusive prefix over devices: capacity absorbed by
            # lower-index devices comes first (device-major worker order)
            all_sums = jax.lax.all_gather(local_sum, axis)  # (D,)
            offset = jnp.sum(jnp.where(jnp.arange(n_dev) < my_dev, all_sums, 0))
            local_remaining = jnp.clip(
                remaining_global - offset, 0, local_sum
            )
            # scarcity-aware order within the local shard
            unneeded = (free > 0) & (need[None, :] == 0)
            waste = jnp.sum(unneeded * scarcity[None, :], axis=1)
            waste_q = jnp.round(waste * _WASTE_Q).astype(jnp.int32)
            idx = jnp.arange(cap.shape[0], dtype=jnp.int32)
            order_key = jnp.where(
                cap > 0, waste_q * cap.shape[0] + idx, jnp.int32(2**31 - 1)
            )
            assign, assigned_local = _water_fill(cap, local_remaining, order_key)
            assigned_global = jax.lax.psum(assigned_local, axis)
            remaining_global = remaining_global - assigned_global
            free = free - assign[:, None] * need[None, :]
            nt_free = nt_free - assign
            counts_v.append(assign)
        return (free, nt_free), jnp.stack(counts_v)

    (free, nt_free), counts = jax.lax.scan(
        batch_body, (free, nt_free), (needs, sizes, min_time)
    )
    return counts, free, nt_free


@functools.partial(jax.jit, static_argnames=("mesh",))
def sharded_cut_scan(
    mesh: Mesh, free, nt_free, lifetime, needs, sizes, min_time, scarcity
):
    """Worker-sharded variant of ops.assign.greedy_cut_scan.

    free (W, R), nt_free/lifetime (W,) sharded on axis "w"; needs/sizes/
    min_time/scarcity replicated. Returns counts (B, V, W) sharded on W.
    """
    return jax.shard_map(
        _sharded_body,
        mesh=mesh,
        in_specs=(
            P("w", None),   # free
            P("w"),         # nt_free
            P("w"),         # lifetime
            P(),            # needs
            P(),            # sizes
            P(),            # min_time
            P(),            # scarcity
        ),
        out_specs=(P(None, None, "w"), P("w", None), P("w")),
        check_vma=False,
    )(free, nt_free, lifetime, needs, sizes, min_time, scarcity)


def place_tick_inputs(mesh: Mesh, free, nt_free, lifetime, needs, sizes,
                      min_time, scarcity):
    """Device-put the tick tensors with the proper shardings."""
    w2 = NamedSharding(mesh, P("w", None))
    w1 = NamedSharding(mesh, P("w"))
    rep = NamedSharding(mesh, P())
    return (
        jax.device_put(free, w2),
        jax.device_put(nt_free, w1),
        jax.device_put(lifetime, w1),
        jax.device_put(needs, rep),
        jax.device_put(sizes, rep),
        jax.device_put(min_time, rep),
        jax.device_put(scarcity, rep),
    )
