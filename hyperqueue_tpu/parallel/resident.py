"""Device-resident tick state: delta uploads + donated solve buffers.

The end-to-end device solve used to pay a full `device_put` of the padded
(W, R)/(W,) state every tick, even though the tick-over-tick delta is tiny:
the solve itself already computes `free_after`/`nt_after` ON the device, and
only the rows touched by task completions (and other host-side bookkeeping)
between two ticks actually differ from what the device would predict.

`DeviceResidency` keeps the padded solver state alive on the accelerator
across ticks and makes each solve pay only for what changed:

- the device arrays (`free`, `nt_free`, `lifetime`, `total`) stay resident,
  sharded over the worker mesh for the multichip model or on the single
  device for the greedy model;
- a HOST MIRROR (plain numpy, one per array) tracks the device contents
  exactly; each tick the new padded inputs are row-diffed against the
  mirror and only the dirty rows are scatter-updated on device (bucketed
  row counts keep the compiled scatter programs few);
- the solve runs with `free`/`nt_free` DONATED (ops/assign.greedy_cut_scan
  and parallel/solve.sharded_cut_scan_donate), so `free_after`/`nt_after`
  of solve N become the resident inputs of solve N+1 with zero host
  traffic; the mirror is re-synchronized from a readback of the (small)
  `free_after`/`nt_after` arrays that rides the same device round trip as
  the counts (`apply_outputs`);
- small replicated inputs (needs / sizes / min_time / class_m / order_ids)
  are placement-cached by content: a steady-state tick that repeats the
  same batch layout re-uses the device buffers outright.

Correctness contract: the resident path must be BIT-IDENTICAL to a fresh
full-upload solve of the same padded inputs.  `models/greedy.py` exposes it
as a paranoid mode (`--paranoid-tick` re-solves from scratch and asserts
count equality) and tests/test_parallel.py drives a randomized multi-tick
soak with worker churn through it.  Anything this module cannot track
exactly — a dropped pipeline dispatch whose outputs were never read back, a
bucket-shape change, a watchdog fallback that bypassed the device — calls
`invalidate()` and the next tick falls back to one full upload.
"""

from __future__ import annotations

import numpy as np

from hyperqueue_tpu.models.greedy import _bucket

# dirty-row fraction above which one full upload beats the gather+scatter
# round (the scatter path costs an index gather on host + a scatter program
# on device; at >=half the rows the dense put is strictly simpler)
FULL_UPLOAD_FRACTION = 0.5

# dirty-row counts are bucketed to powers of two (floor 16, the shared
# models/greedy._bucket rule) so the jitted scatter programs stay few;
# padding repeats the first dirty row (a duplicate .set() with an
# identical payload is order-independent)
_ROW_BUCKET_FLOOR = 16


def _scatter_rows(dst, idx, vals):
    return dst.at[idx].set(vals)


class DeviceResidency:
    """Resident device buffers + host mirror for one solver's padded state.

    Shardings: `shardings` is the (w2, w1, rep) NamedSharding triple for a
    mesh (parallel/solve._mesh_shardings), or None for single-device
    placement (optionally pinned with `device`).
    """

    def __init__(self, shardings=None, device=None):
        self._shardings = shardings
        self._device = device
        self.key = None            # (pw, pr, has_total) of the resident state
        self.free = None           # device (pw, pr) int32
        self.nt_free = None        # device (pw,) int32
        self.lifetime = None       # device (pw,) int32
        self.total = None          # device (pw, pr) int32 (ALL-policy only)
        self._m_free = None        # host mirrors of the device contents
        self._m_nt = None
        self._m_life = None
        self._m_total = None
        self._valid = False
        # set between a donated solve and apply_counts()/invalidate():
        # while True the mirror does NOT reflect the device (the device
        # holds free_after) and sync() must not run
        self._await_apply = False
        # replicated-input placement cache: name -> (host copy, device arr)
        self._rep_cache: dict = {}
        self._scatter_jit = None
        # telemetry (scraped via the model's resident_stats())
        self.full_uploads = 0
        self.delta_uploads = 0
        self.dirty_rows_last = 0
        self.upload_bytes_total = 0
        self.rep_cache_hits = 0
        self.invalidations = 0

    # -- placement helpers ------------------------------------------------
    def _put(self, arr, kind):
        import jax

        if self._shardings is not None:
            return jax.device_put(arr, self._shardings[kind])
        if self._device is not None:
            return jax.device_put(arr, self._device)
        return jax.device_put(arr)

    def _scatter(self, dst, idx, vals, kind):
        import jax

        if self._scatter_jit is None:
            if self._shardings is not None:
                w2, w1, _rep = self._shardings[:3]
                self._scatter_jit = (
                    jax.jit(_scatter_rows, donate_argnums=(0,),
                            out_shardings=w2),
                    jax.jit(_scatter_rows, donate_argnums=(0,),
                            out_shardings=w1),
                )
            else:
                fn = jax.jit(_scatter_rows, donate_argnums=(0,))
                self._scatter_jit = (fn, fn)
        return self._scatter_jit[kind](dst, idx, vals)

    # -- the per-tick sync ------------------------------------------------
    def sync(self, free_p, nt_p, life_p, total_p=None):
        """Bring the resident device state up to date with this tick's
        padded host inputs; returns (free, nt_free, lifetime, total) device
        arrays.  Full upload when nothing is resident (or too much changed),
        dirty-row scatter otherwise."""
        if self._await_apply:
            # the previous solve's counts were never applied to the mirror
            # (e.g. a dropped pipeline dispatch): residency is unknowable
            self.invalidate()
        pw, pr = free_p.shape
        key = (pw, pr, total_p is not None)
        if not self._valid or key != self.key:
            return self._full_upload(key, free_p, nt_p, life_p, total_p)

        dirty = (self._m_free != free_p).any(axis=1)
        np.logical_or(dirty, self._m_nt != nt_p, out=dirty)
        np.logical_or(dirty, self._m_life != life_p, out=dirty)
        if total_p is not None:
            np.logical_or(
                dirty, (self._m_total != total_p).any(axis=1), out=dirty
            )
        rows = np.nonzero(dirty)[0]
        self.dirty_rows_last = int(rows.size)
        if rows.size == 0:
            return self.free, self.nt_free, self.lifetime, self.total
        if rows.size > pw * FULL_UPLOAD_FRACTION:
            return self._full_upload(key, free_p, nt_p, life_p, total_p)

        k = _bucket(int(rows.size), _ROW_BUCKET_FLOOR)
        idx = np.empty(k, dtype=np.int32)
        idx[: rows.size] = rows
        idx[rows.size:] = rows[0]  # idempotent duplicate scatter padding
        idx_d = self._put(idx, 2)
        self.free = self._scatter(self.free, idx_d, self._put(free_p[idx], 2),
                                  0)
        self.nt_free = self._scatter(
            self.nt_free, idx_d, self._put(nt_p[idx], 2), 1
        )
        self.lifetime = self._scatter(
            self.lifetime, idx_d, self._put(life_p[idx], 2), 1
        )
        if total_p is not None:
            self.total = self._scatter(
                self.total, idx_d, self._put(total_p[idx], 2), 0
            )
        self._m_free[rows] = free_p[rows]
        self._m_nt[rows] = nt_p[rows]
        self._m_life[rows] = life_p[rows]
        if total_p is not None:
            self._m_total[rows] = total_p[rows]
        self.delta_uploads += 1
        self.upload_bytes_total += int(
            k * (free_p.itemsize * pr * (2 if total_p is not None else 1)
                 + nt_p.itemsize + life_p.itemsize + idx.itemsize)
        )
        return self.free, self.nt_free, self.lifetime, self.total

    def _full_upload(self, key, free_p, nt_p, life_p, total_p):
        self.key = key
        self.free = self._put(free_p, 0)
        self.nt_free = self._put(nt_p, 1)
        self.lifetime = self._put(life_p, 1)
        self.total = None if total_p is None else self._put(total_p, 0)
        self._m_free = free_p.copy()
        self._m_nt = nt_p.copy()
        self._m_life = life_p.copy()
        self._m_total = None if total_p is None else total_p.copy()
        self._valid = True
        self.dirty_rows_last = free_p.shape[0]
        self.full_uploads += 1
        self.upload_bytes_total += int(
            free_p.nbytes + nt_p.nbytes + life_p.nbytes
            + (0 if total_p is None else total_p.nbytes)
        )
        return self.free, self.nt_free, self.lifetime, self.total

    # -- donated-solve bookkeeping ---------------------------------------
    def adopt_outputs(self, free_after, nt_after) -> None:
        """The donated solve consumed `free`/`nt_free`; the returned
        `free_after`/`nt_after` device arrays ARE the next tick's resident
        inputs.  The mirror is stale until apply_counts() replays the
        solve's assignment deltas."""
        self.free = free_after
        self.nt_free = nt_after
        self._await_apply = True

    def apply_outputs(self, free_after_host, nt_after_host) -> None:
        """Re-synchronize the mirror with the donated outputs: the caller
        reads `free_after`/`nt_after` back alongside the counts (one round
        trip) and hands the host arrays here.  Copied because jax readbacks
        can be non-writable views and the mirror must accept row scatters.

        This is exact for EVERY kernel feature (including ALL-policy pool
        zeroing) because the mirror is literally the device's output."""
        if not self._await_apply:
            return
        self._m_free = np.array(free_after_host, dtype=np.int32, copy=True)
        self._m_nt = np.array(nt_after_host, dtype=np.int32, copy=True)
        self._await_apply = False

    def invalidate(self) -> None:
        """Drop residency: the next sync() performs a full upload.  Called
        whenever the device state can no longer be tracked exactly (ALL-
        policy solve, watchdog fallback mid-pipeline, abandoned dispatch)."""
        if self._valid or self._await_apply:
            self.invalidations += 1
        self._valid = False
        self._await_apply = False
        self.free = self.nt_free = self.lifetime = self.total = None
        self._m_free = self._m_nt = self._m_life = self._m_total = None

    # -- replicated-input placement cache --------------------------------
    def place_cached(self, name: str, arr, kind: int = 2):
        """Device-put `arr` with placement caching by CONTENT: if the same
        array bytes were placed under `name` last tick, the existing device
        buffer is reused (steady-state ticks repeat the batch layout and
        class tables exactly).  The host copy is defensive — callers reuse
        and mutate their padded buffers in place across ticks."""
        if arr is None:
            return None
        cached = self._rep_cache.get(name)
        if (
            cached is not None
            and cached[0].shape == arr.shape
            and cached[0].dtype == arr.dtype
            and np.array_equal(cached[0], arr)
        ):
            self.rep_cache_hits += 1
            return cached[1]
        dev = self._put(arr, kind)
        self._rep_cache[name] = (arr.copy(), dev)
        self.upload_bytes_total += int(arr.nbytes)
        return dev

    # -- telemetry --------------------------------------------------------
    def stats(self) -> dict:
        return {
            "resident": bool(self._valid),
            "full_uploads": self.full_uploads,
            "delta_uploads": self.delta_uploads,
            "dirty_rows_last": self.dirty_rows_last,
            "upload_bytes_total": self.upload_bytes_total,
            "rep_cache_hits": self.rep_cache_hits,
            "invalidations": self.invalidations,
        }
