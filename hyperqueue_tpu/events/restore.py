"""Crash-resume: replay the journal into a fresh server state.

Reference: crates/hyperqueue/src/server/restore.rs — StateRestorer replays
events, reconstructs jobs/open-state, re-submits unfinished tasks into the
core with preserved instance/crash counters (gateway.rs:201-205) so stale
messages from pre-crash workers are discarded; finished tasks are skipped and
their dependents see them as satisfied.
"""

from __future__ import annotations

import logging

from hyperqueue_tpu.events.journal import Journal
from hyperqueue_tpu.ids import make_task_id
from hyperqueue_tpu.server import reactor
from hyperqueue_tpu.server.protocol import (
    expand_desc_tasks,
    rqv_from_wire,
    submit_record,
)
from hyperqueue_tpu.server.task import Task

logger = logging.getLogger("hq.restore")

TERMINAL = {"task-finished": "finished", "task-failed": "failed",
            "task-canceled": "canceled"}


def restore_from_journal(server) -> None:
    """Replay server.journal_path into server.jobs/server.core."""
    task_status: dict[tuple[int, int], tuple[str, str]] = {}
    task_instances: dict[tuple[int, int], int] = {}
    job_descs: dict[int, list[dict]] = {}
    n_events = 0

    for record in Journal.read_all(server.journal_path):
        n_events += 1
        # continue the event sequence where the journal left off so
        # stream-with-history seq dedup stays monotonic across restarts
        seq = record.get("seq")
        if isinstance(seq, int) and seq >= server._event_seq:
            server._event_seq = seq + 1
        kind = record.get("event")
        job_id = record.get("job")
        if kind == "job-submitted":
            desc = record.get("desc") or {}
            job = server.jobs.jobs.get(job_id)
            if job is None:
                job = server.jobs.create_job(
                    name=desc.get("name", "job"),
                    submit_dir=desc.get("submit_dir", "/"),
                    max_fails=desc.get("max_fails"),
                    is_open=desc.get("open", False),
                    job_id=job_id,
                )
            expanded = expand_desc_tasks(desc)
            for t in expanded:
                server.jobs.attach_task(job, t.get("id", 0))
            job.submits.append(submit_record(desc, len(expanded)))
            job_descs.setdefault(job_id, []).extend(expanded)
        elif kind == "job-opened":
            if job_id not in server.jobs.jobs:
                server.jobs.create_job(
                    name=record.get("name", "job"),
                    submit_dir=record.get("submit_dir", "/"),
                    is_open=True,
                    job_id=job_id,
                )
        elif kind == "job-closed":
            job = server.jobs.jobs.get(job_id)
            if job is not None:
                job.is_open = False
        elif kind == "job-completed":
            job = server.jobs.jobs.get(job_id)
            if job is not None and record.get("cancel_reason"):
                job.cancel_reason = record["cancel_reason"]
        elif kind in TERMINAL:
            task_status[(job_id, record["task"])] = (
                TERMINAL[kind],
                record.get("error", ""),
            )
        elif kind == "task-started":
            key = (job_id, record["task"])
            task_instances[key] = task_instances.get(key, 0) + 1

    # apply terminal statuses to job counters
    for (job_id, task_id), (status, error) in task_status.items():
        job = server.jobs.jobs.get(job_id)
        if job is None or task_id not in job.tasks:
            continue
        info = job.tasks[task_id]
        info.status = status
        info.error = error
        job.counters[status] += 1

    # re-submit unfinished tasks into the core
    resubmitted = 0
    for job_id, descs in job_descs.items():
        job = server.jobs.jobs.get(job_id)
        if job is None:
            continue
        new_tasks = []
        for t in descs:
            job_task_id = t.get("id", 0)
            if (job_id, job_task_id) in task_status:
                continue  # already terminal
            rqv = rqv_from_wire(t.get("request") or {}, server.core.resource_map)
            rq_id = server.core.intern_rqv(rqv)
            deps = tuple(
                make_task_id(job_id, d)
                for d in t.get("deps", ())
                if task_status.get((job_id, d), ("",))[0] != "finished"
            )
            # failed/canceled dependency => this task can never run; mark it
            dead_dep = any(
                task_status.get((job_id, d), ("",))[0] in ("failed", "canceled")
                for d in t.get("deps", ())
            )
            if dead_dep:
                job.tasks[job_task_id].status = "canceled"
                job.counters["canceled"] += 1
                continue
            task = Task(
                task_id=make_task_id(job_id, job_task_id),
                rq_id=rq_id,
                priority=(int(t.get("priority", 0)), -job_id),
                body=t.get("body", {}),
                entry=t.get("entry"),
                deps=deps,
                crash_limit=int(t.get("crash_limit", 5)),
            )
            # preserved instance counter: stale pre-crash worker messages
            # carry older instance ids and are dropped (reference
            # gateway.rs:204 adjust_instance_id_and_crash_counters)
            task.instance_id = task_instances.get((job_id, job_task_id), 0)
            new_tasks.append(task)
        if new_tasks:
            reactor.on_new_tasks(server.core, server.comm, new_tasks)
            resubmitted += len(new_tasks)
    logger.info(
        "restored %d jobs (%d events, %d tasks resubmitted) from %s",
        len(server.jobs.jobs),
        n_events,
        resubmitted,
        server.journal_path,
    )
