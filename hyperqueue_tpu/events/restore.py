"""Crash-resume: replay the journal into a fresh server state.

Reference: crates/hyperqueue/src/server/restore.rs — StateRestorer replays
events, reconstructs jobs/open-state, re-submits unfinished tasks into the
core with preserved instance/crash counters (gateway.rs:201-205) so stale
messages from pre-crash workers are discarded; finished tasks are skipped and
their dependents see them as satisfied.
"""

from __future__ import annotations

import logging

from hyperqueue_tpu.events.journal import Journal
from hyperqueue_tpu.ids import make_task_id
from hyperqueue_tpu.server import reactor
from hyperqueue_tpu.server.protocol import (
    expand_desc_tasks,
    rqv_from_wire,
    submit_record,
)
from hyperqueue_tpu.server.task import Task

logger = logging.getLogger("hq.restore")

TERMINAL = {"task-finished": "finished", "task-failed": "failed",
            "task-canceled": "canceled"}


def restore_from_journal(server) -> None:
    """Replay server.journal_path into server.jobs/server.core.

    Tasks that were RUNNING at the crash (a task-started with no terminal
    event) are held in server.reattach_pending instead of being requeued:
    their pre-crash worker keeps running them through the outage
    (`--on-server-lost reconnect`) and reclaims them at re-registration
    with the preserved instance id. Only when no worker reclaims a task
    within `--reattach-timeout` is it fenced (instance bump) and requeued
    (see Server._reattach_reaper). With the window disabled the fence +
    requeue happens here, the pre-reattach behavior.
    """
    task_status: dict[tuple[int, int], tuple[str, str]] = {}
    # terminal event wall-clock per task (timeline: finished_at survives)
    task_finished_at: dict[tuple[int, int], float] = {}
    # lifecycle stamps of the LAST start per task: (queued, assigned,
    # started) — `hq job timeline` keeps one unbroken span across a server
    # restart + reattach instead of restarting the clock
    task_started_at: dict[tuple[int, int], tuple[float, float, float]] = {}
    # highest instance id the journal saw per task (last task-started OR
    # task-restarted — a restart bumps the instance without a new start);
    # the live pre-crash worker holds at most this instance
    task_instances: dict[tuple[int, int], int] = {}
    # True while the LAST lifecycle event was a start (the task may still
    # be running on a reconnecting worker); a later restart clears it
    task_maybe_running: dict[tuple[int, int], bool] = {}
    task_variants: dict[tuple[int, int], int] = {}
    task_crashes: dict[tuple[int, int], int] = {}
    job_descs: dict[int, list[dict]] = {}
    n_events = 0
    # restore generation: every prior boot that owned this journal wrote
    # one server-uid record (before any task event of its lifetime). Each
    # boot can have issued instances whose lifecycle events (start,
    # requeue, restart — every one a bump) died in its unflushed tail, so
    # neither "the journal never saw a start" nor "the last journaled
    # instance was i" bounds what actually ran. Fencing below jumps to
    # this boot's generation base (n_boots * stride), past everything a
    # prior boot could have issued.
    n_boots = 0

    for record in Journal.read_all(server.journal_path):
        n_events += 1
        # continue the event sequence where the journal left off so
        # stream-with-history seq dedup stays monotonic across restarts
        seq = record.get("seq")
        if isinstance(seq, int) and seq >= server._event_seq:
            server._event_seq = seq + 1
        kind = record.get("event")
        job_id = record.get("job")
        if kind == "job-submitted":
            desc = record.get("desc") or {}
            job = server.jobs.jobs.get(job_id)
            if job is None:
                job = server.jobs.create_job(
                    name=desc.get("name", "job"),
                    submit_dir=desc.get("submit_dir", "/"),
                    max_fails=desc.get("max_fails"),
                    is_open=desc.get("open", False),
                    job_id=job_id,
                )
            submit_time = float(record.get("time", 0.0))
            if submit_time and (
                not job.tasks or submit_time < job.submitted_at
            ):
                job.submitted_at = submit_time
            expanded = expand_desc_tasks(desc)
            for t in expanded:
                server.jobs.attach_task(job, t.get("id", 0))
                if submit_time:
                    # keep the ORIGINAL submit clock, not the restore's
                    job.tasks[t.get("id", 0)].submitted_at = submit_time
            job.submits.append(submit_record(desc, len(expanded)))
            job_descs.setdefault(job_id, []).extend(expanded)
        elif kind == "job-opened":
            if job_id not in server.jobs.jobs:
                server.jobs.create_job(
                    name=record.get("name", "job"),
                    submit_dir=record.get("submit_dir", "/"),
                    is_open=True,
                    job_id=job_id,
                )
        elif kind == "job-closed":
            job = server.jobs.jobs.get(job_id)
            if job is not None:
                job.is_open = False
        elif kind == "job-completed":
            job = server.jobs.jobs.get(job_id)
            if job is not None and record.get("cancel_reason"):
                job.cancel_reason = record["cancel_reason"]
        elif kind in TERMINAL:
            task_status[(job_id, record["task"])] = (
                TERMINAL[kind],
                record.get("error", ""),
            )
            task_finished_at[(job_id, record["task"])] = float(
                record.get("time", 0.0)
            )
        elif kind == "task-started":
            key = (job_id, record["task"])
            task_instances[key] = max(
                record.get("instance", 0), task_instances.get(key, 0)
            )
            task_variants[key] = record.get("variant", 0)
            task_maybe_running[key] = True
            task_started_at[key] = (
                float(record.get("queued_at", 0.0)),
                float(record.get("assigned_at", 0.0)),
                float(record.get("started_at", 0.0))
                or float(record.get("time", 0.0)),
            )
        elif kind == "task-restarted":
            key = (job_id, record["task"])
            task_crashes[key] = record.get(
                "crash_count", task_crashes.get(key, 0)
            )
            task_instances[key] = max(
                record.get("instance", 0), task_instances.get(key, 0)
            )
            task_maybe_running[key] = False
        elif kind == "server-uid":
            server.journal_uids.add(record.get("server_uid") or "")
            n_boots += 1

    # apply terminal statuses to job counters (with the ORIGINAL clock so
    # `hq job timeline` of a restored job reports true phase durations)
    for (job_id, task_id), (status, error) in task_status.items():
        job = server.jobs.jobs.get(job_id)
        if job is None or task_id not in job.tasks:
            continue
        info = job.tasks[task_id]
        info.status = status
        info.error = error
        info.finished_at = task_finished_at.get((job_id, task_id), 0.0)
        stamps = task_started_at.get((job_id, task_id))
        if stamps is not None:
            info.started_at = stamps[2]
        job.counters[status] += 1

    # re-submit unfinished tasks into the core
    from hyperqueue_tpu.server.task import INSTANCE_GENERATION_STRIDE

    fence_floor = max(n_boots, 1) * INSTANCE_GENERATION_STRIDE
    server.core.instance_fence_floor = fence_floor
    resubmitted = 0
    held = 0
    reattach_window = getattr(server, "reattach_timeout", 0.0)
    import time as _time

    reattach_deadline = _time.monotonic() + reattach_window
    for job_id, descs in job_descs.items():
        job = server.jobs.jobs.get(job_id)
        if job is None:
            continue
        new_tasks = []
        for t in descs:
            job_task_id = t.get("id", 0)
            key = (job_id, job_task_id)
            if key in task_status:
                continue  # already terminal
            rqv = rqv_from_wire(t.get("request") or {}, server.core.resource_map)
            rq_id = server.core.intern_rqv(rqv)
            deps = tuple(
                make_task_id(job_id, d)
                for d in t.get("deps", ())
                if task_status.get((job_id, d), ("",))[0] != "finished"
            )
            # failed/canceled dependency => this task can never run; mark it
            dead_dep = any(
                task_status.get((job_id, d), ("",))[0] in ("failed", "canceled")
                for d in t.get("deps", ())
            )
            if dead_dep:
                job.tasks[job_task_id].status = "canceled"
                job.counters["canceled"] += 1
                continue
            task = Task(
                task_id=make_task_id(job_id, job_task_id),
                rq_id=rq_id,
                priority=(int(t.get("priority", 0)), -job_id),
                body=t.get("body", {}),
                entry=t.get("entry"),
                deps=deps,
                crash_limit=int(t.get("crash_limit", 5)),
            )
            task.crash_counter = task_crashes.get(key, 0)
            started_instance = task_instances.get(key)
            if started_instance is None:
                # never started AS FAR AS THE JOURNAL KNOWS. The start —
                # or a whole start/requeue/restart chain — may sit in the
                # crashed boot's lost tail (worker uplink coalescing + the
                # in-flight journal batch) while an incarnation still runs
                # on a reconnecting worker; re-issuing at an id that chain
                # reached would execute one instance twice, invisible to
                # the (task, instance) equality fence. Jumping to this
                # boot's generation base clears every id any prior boot
                # could have issued; the reconnecting worker's stale claim
                # is then discarded and its copy killed at re-registration.
                task.fence_instance(fence_floor)
                new_tasks.append(task)
                continue
            # preserved instance id: stale pre-crash worker messages carry
            # older instance ids and are dropped (reference gateway.rs:204
            # adjust_instance_id_and_crash_counters)
            task.instance_id = started_instance
            task.assigned_variant = task_variants.get(key, 0)
            if (
                reattach_window > 0
                and task_maybe_running.get(key)
                and not rqv.is_multi_node
            ):
                # maybe still running on a reconnecting worker: hold it out
                # of the queues (state WAITING, deps all finished) until a
                # worker reclaims it or the window expires. Gangs are never
                # held — a partial gang reattach is worthless, so they are
                # fenced + requeued like before.
                stamps = task_started_at.get(key)
                if stamps is not None:
                    # pre-seed the lifecycle chain from the journal: on
                    # reattach the task keeps its ORIGINAL start (one
                    # unbroken timeline, no duplicate spawn phase)
                    task.t_ready, task.t_assigned, task.t_started = stamps
                server.core.tasks[task.task_id] = task
                server.reattach_pending[task.task_id] = reattach_deadline
                held += 1
            else:
                # fence out the pre-crash incarnation (and anything past
                # it in the lost tail) and requeue now
                task.fence_instance(fence_floor)
                new_tasks.append(task)
        if new_tasks:
            reactor.on_new_tasks(server.core, server.comm, new_tasks)
            resubmitted += len(new_tasks)
    logger.info(
        "restored %d jobs (%d events, %d tasks resubmitted, %d held for "
        "reattach) from %s",
        len(server.jobs.jobs),
        n_events,
        resubmitted,
        held,
        server.journal_path,
    )
