"""Crash-resume: snapshot load + journal tail replay into a fresh server.

Reference: crates/hyperqueue/src/server/restore.rs — StateRestorer replays
events, reconstructs jobs/open-state, re-submits unfinished tasks into the
core with preserved instance/crash counters (gateway.rs:201-205) so stale
messages from pre-crash workers are discarded; finished tasks are skipped and
their dependents see them as satisfied.

Two-phase bounded restore (events/snapshot.py): phase 1 loads the newest
valid snapshot — seeding the SAME accumulators a journal replay fills, so
everything downstream is one code path — and phase 2 replays only journal
records at/after the snapshot's event-seq watermark. A torn/corrupt
snapshot falls back to the previous snapshot, then to a full replay.
Restore cost is O(live state + tail), not O(history).
"""

from __future__ import annotations

import logging
import time

from hyperqueue_tpu.events import snapshot as snapshot_mod
from hyperqueue_tpu.events.journal import Journal
from hyperqueue_tpu.ids import make_task_id
from hyperqueue_tpu.scheduler.queues import encode_sched_priority
from hyperqueue_tpu.server import reactor
from hyperqueue_tpu.server.jobs import JobManager
from hyperqueue_tpu.server.protocol import (
    expand_desc_tasks,
    rqv_from_wire,
    submit_record,
)
from hyperqueue_tpu.server.task import Task
from hyperqueue_tpu.utils.metrics import REGISTRY
from hyperqueue_tpu.utils import clock

logger = logging.getLogger("hq.restore")

TERMINAL = {"task-finished": "finished", "task-failed": "failed",
            "task-canceled": "canceled"}

# restores are rare; the histogram's job is distinguishing "instant" from
# "the journal needs compaction" — hence buckets out to a minute
_RESTORE_SECONDS = REGISTRY.histogram(
    "hq_restore_duration_seconds",
    "journal/snapshot restore duration at server start",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0),
)


class _RestoreAcc:
    """The replay accumulators: filled by a snapshot seed and/or journal
    records, applied to the server once at the end."""

    def __init__(self):
        self.task_status: dict[tuple[int, int], tuple[str, str]] = {}
        # terminal event wall-clock per task (timeline: finished_at survives)
        self.task_finished_at: dict[tuple[int, int], float] = {}
        # lifecycle stamps of the LAST start per task: (queued, assigned,
        # started) — `hq job timeline` keeps one unbroken span across a
        # server restart + reattach instead of restarting the clock
        self.task_started_at: dict[tuple[int, int],
                                   tuple[float, float, float]] = {}
        # highest instance id seen per task (journal: last task-started OR
        # task-restarted; snapshot: the live instance at capture)
        self.task_instances: dict[tuple[int, int], int] = {}
        # True while the LAST lifecycle event was a start (the task may
        # still be running on a reconnecting worker)
        self.task_maybe_running: dict[tuple[int, int], bool] = {}
        self.task_variants: dict[tuple[int, int], int] = {}
        self.task_crashes: dict[tuple[int, int], int] = {}
        self.job_descs: dict[int, list[dict]] = {}
        # distributed-trace reconstruction (ISSUE 8): trace ids + stamps
        # replayed from events (or seeded whole from a snapshot), so a
        # restored server answers `hq task trace` with the SAME unbroken
        # trace the crashed one was assembling
        self.task_submit_trace: dict[tuple[int, int], dict] = {}
        self.task_wtrace: dict[tuple[int, int], dict] = {}
        # lend annotations accumulate across task-started events — a
        # later home-shard restart must not erase an earlier
        # borrowed-worker start's note (the live store keeps every note)
        self.task_lends: dict[tuple[int, int], list] = {}
        self.task_finish_wtrace: dict[tuple[int, int], dict] = {}
        self.task_trace_seed: dict[int, dict] = {}
        # unmaterialized lazy array chunks from a snapshot (ISSUE 10):
        # (job_id, spec) pairs registered into the core's LazyStore at the
        # end of restore, AFTER the journal tail names which of their ids
        # gained per-task state and must materialize eagerly instead
        self.lazy_chunks: list[tuple[int, dict]] = []
        # restore generation: every boot that owned this journal wrote one
        # server-uid record; a snapshot folds the pre-watermark count into
        # n_boots and tail records add to it. Fencing jumps re-issued tasks
        # to n_boots * stride, past everything a prior boot could have
        # issued in its lost journal tail.
        self.n_boots = 0
        # allocation-exact restore (ISSUE 13): queue_id -> queue wire dict
        # (with "_allocs": {alloc_id: alloc wire}) rebuilt from the
        # snapshot's autoalloc table + alloc-* journal-tail events
        self.autoalloc: dict[int, dict] = {}
        self.next_alloc_queue_id = 1
        # alloc-submit-attempt records with no journaled outcome: possible
        # orphans the service pidfile-scans at start (_adopt_orphans)
        self.alloc_attempts: list[dict] = []
        # elastic resharding (ISSUE 17): jobs sealed for export whose
        # migration had not finalized at the crash (job_id -> out record);
        # they restore held/paused until the coordinator re-drives or
        # aborts the migration
        self.migrating_out: dict[int, dict] = {}
        # jobs this shard handed off (migration-out-done replayed):
        # job_id -> destination shard, for wrong-shard redirects
        self.migrated_out: dict[int, int] = {}


def _seed_autoalloc(acc: _RestoreAcc, table: dict | None) -> None:
    if not table:
        return
    for qd in table.get("queues") or ():
        q = dict(qd)
        q["_allocs"] = {a["id"]: dict(a) for a in q.pop("allocations", ())}
        acc.autoalloc[q["id"]] = q
    acc.next_alloc_queue_id = max(
        acc.next_alloc_queue_id, table.get("next_queue_id", 1)
    )
    acc.alloc_attempts.extend(dict(a) for a in table.get("attempts") or ())


def _replay_alloc_record(acc: _RestoreAcc, kind: str, record: dict) -> None:
    """One alloc-* journal record into the allocation accumulator. The
    wire shapes mirror state.py to_wire/from_wire exactly, so the service
    rebuilds the table the crashed server held at its last journal write."""
    qid = record.get("queue_id")
    if qid is None:
        return
    if kind == "alloc-queue-created":
        acc.autoalloc[qid] = {
            "id": qid, "state": "running",
            "params": record.get("params")
            or {"manager": record.get("manager", "slurm")},
            "consecutive_failures": 0, "_allocs": {},
        }
        acc.next_alloc_queue_id = max(acc.next_alloc_queue_id, qid + 1)
        return
    queue = acc.autoalloc.get(qid)
    if queue is None:
        return  # e.g. the probe queue, never created
    if kind == "alloc-queue-removed":
        acc.autoalloc.pop(qid, None)
        acc.alloc_attempts = [
            a for a in acc.alloc_attempts if a.get("queue_id") != qid
        ]
    elif kind == "alloc-queue-paused":
        queue["state"] = "paused"
    elif kind == "alloc-queue-resumed":
        queue["state"] = "running"
        queue["quarantine_until"] = 0.0
    elif kind == "alloc-queue-quarantined":
        queue["state"] = "quarantined"
        queue["quarantine_until"] = float(record.get("until", 0.0))
        queue["quarantines"] = int(record.get("quarantines", 1))
    elif kind == "alloc-submit-attempt":
        acc.alloc_attempts.append(
            {"queue_id": qid, "workdir": record.get("workdir", "")}
        )
    elif kind == "alloc-submit-failed":
        _pop_attempt(acc, qid)
    elif kind == "alloc-queued":
        _pop_attempt(acc, qid)
        aid = record.get("alloc")
        if aid:
            queue["_allocs"][aid] = {
                "id": aid, "queue": qid,
                "worker_count": record.get("worker_count", 1),
                "status": "queued",
                "queued_at": float(record.get("time", 0.0)),
                "workdir": record.get("workdir", ""),
            }
    else:
        alloc = queue["_allocs"].get(record.get("alloc"))
        if alloc is None:
            return
        if kind == "alloc-started":
            alloc["status"] = "running"
            if not alloc.get("started_at"):
                alloc["started_at"] = float(record.get("time", 0.0))
        elif kind == "alloc-worker-bound":
            alloc["ever_bound"] = True
            if alloc["status"] == "queued":
                alloc["status"] = "running"
                alloc["started_at"] = float(record.get("time", 0.0))
        elif kind in ("alloc-finished", "alloc-failed", "alloc-cancelled"):
            alloc["status"] = kind[len("alloc-"):]
            alloc["ended_at"] = float(record.get("time", 0.0))
            if record.get("reason"):
                alloc["reason"] = record["reason"]


def _pop_attempt(acc: _RestoreAcc, qid: int) -> None:
    """An attempt's outcome landed in the journal: it is not an orphan."""
    for i, attempt in enumerate(acc.alloc_attempts):
        if attempt.get("queue_id") == qid:
            del acc.alloc_attempts[i]
            return


def _seed_from_snapshot(server, acc: _RestoreAcc, state: dict) -> None:
    """Phase 1: install a snapshot as if the pre-watermark journal had just
    been replayed. Touches only server.jobs/_event_seq/journal_uids and the
    accumulators, so a failure can be rolled back before falling back to
    the previous snapshot or a full replay."""
    bodies = state["bodies"]
    requests = state["requests"]
    for jd in state["jobs"]:
        seed_job(server, acc, jd, bodies, requests)
    for task_id, rec in (state.get("traces") or {}).items():
        acc.task_trace_seed[int(task_id)] = rec
    _seed_autoalloc(acc, state.get("autoalloc"))
    acc.n_boots = state["n_boots"]
    server.journal_uids.update(state.get("server_uids") or ())
    # usage ledger at the snapshot watermark (ISSUE 18); None for
    # pre-accounting snapshots — the tail replay refills what it can
    server.accounting.seed(state.get("accounting"))
    if state["seq"] > server._event_seq:
        server._event_seq = state["seq"]
    # forgotten jobs are absent from the snapshot but their ids must not be
    # reused — a reconnecting worker could still hold a forgotten job's
    # task under the same (job, task) id
    server.jobs.job_id_counter.ensure_above(state.get("next_job_id", 1) - 1)


def seed_job(server, acc: _RestoreAcc, jd: dict,
             bodies: list, requests: list) -> None:
    """Seed ONE job (snapshot per-job shape) into server.jobs + the
    accumulators. Shared by the snapshot seed and the migration-record
    import replay (ISSUE 17): a migrated-in job flows through the exact
    path a snapshot-restored one does, so every restore invariant —
    reattach holds, fencing, original clocks — carries over to moves."""
    job_id = jd["id"]
    job = server.jobs.create_job(
        name=jd["name"],
        submit_dir=jd["submit_dir"],
        max_fails=jd["max_fails"],
        is_open=jd["open"],
        job_id=job_id,
    )
    job.submitted_at = jd["submitted_at"]
    job.cancel_reason = jd["cancel_reason"]
    job.submits = list(jd["submits"])
    for tid, status, error, finished_at, started_at, submitted_at in (
        jd["done"]
    ):
        server.jobs.attach_task(job, tid)
        info = job.tasks[tid]
        info.submitted_at = submitted_at
        key = (job_id, tid)
        acc.task_status[key] = (status, error)
        acc.task_finished_at[key] = finished_at
        if started_at:
            acc.task_started_at[key] = (0.0, 0.0, started_at)
    for uid, s in (jd.get("streams") or {}).items():
        job.streams[uid] = {
            "applied": set(s["applied"]), "sealed": bool(s["sealed"]),
        }
        if not s["sealed"]:
            job.open_streams += 1
        server._stream_jobs[uid] = job_id
    for spec in jd.get("lazy") or ():
        resolved = dict(spec)
        resolved["body"] = bodies[spec["b"]]
        resolved["request"] = requests[spec["rq"]]
        acc.lazy_chunks.append((job_id, resolved))
    descs = acc.job_descs.setdefault(job_id, [])
    for t in jd["pending"]:
        tid = t["id"]
        server.jobs.attach_task(job, tid)
        job.tasks[tid].submitted_at = t["submitted_at"]
        desc = {
            "id": tid,
            # index into the shared tables: tasks of one array get the
            # SAME body object back, preserving the identity sharing
            # the compute-message dedup relies on
            "body": bodies[t["b"]],
            "request": requests[t["rq"]],
            "priority": t["priority"],
            "crash_limit": t["crash_limit"],
            "deps": t["deps"],
        }
        if "entry" in t:
            desc["entry"] = t["entry"]
        descs.append(desc)
        key = (job_id, tid)
        if t["crashes"]:
            acc.task_crashes[key] = t["crashes"]
        if t["running"]:
            acc.task_instances[key] = t["instance"]
            acc.task_variants[key] = t["variant"]
            acc.task_maybe_running[key] = True
            acc.task_started_at[key] = tuple(t["stamps"])
        elif t["instance"]:
            # not running, but the instance counter moved (crashes,
            # assignment at capture): restore must fence past it
            acc.task_instances[key] = t["instance"]
            acc.task_variants[key] = t["variant"]
            acc.task_maybe_running[key] = False



def _array_replays_lazy(server, array: dict) -> bool:
    """Should this journaled array desc stay compact through replay?
    Mirrors the live ingest decision (_ingest_array_desc): at/above the
    server's lazy threshold and single-node only (multi-node requests
    never register lazily)."""
    threshold = getattr(server, "lazy_array_threshold", 1 << 62)
    id_range = array.get("id_range")
    n = (
        int(id_range[1]) - int(id_range[0])
        if id_range is not None
        else len(array.get("ids") or ())
    )
    if n < threshold:
        return False
    variants = (array.get("request") or {}).get("variants") or []
    return not any(v.get("n_nodes") for v in variants)


def _seed_migration_record(server, acc: _RestoreAcc, rec: dict) -> None:
    """migration-in replay: re-import the embedded migration record.

    The record is self-contained (fresh bodies/requests tables captured
    by snapshot.capture_job on the source), so replay needs nothing from
    the source shard. Instances are floored at the source's fence
    watermark BEFORE this boot's own fence bump, keeping instance ids
    monotonic across the move — a SIGSTOP'd source resuming later can
    never collide with an incarnation the destination issues."""
    jd = rec.get("job_state") or {}
    job_id = jd.get("id")
    if job_id is None or job_id in server.jobs.jobs:
        return  # duplicate import (a re-driven migration): first wins
    # a returning job (migrated out earlier, now migrating back in)
    # must clear its own wrong-shard tombstone, mirroring the live
    # import path in bootstrap._apply_migration_record
    acc.migrating_out.pop(job_id, None)
    acc.migrated_out.pop(job_id, None)
    seed_job(server, acc, jd, rec.get("bodies") or [],
             rec.get("requests") or [])
    src_fence = int(rec.get("fence", 0))
    for t in jd.get("pending") or ():
        key = (job_id, t["id"])
        if src_fence:
            acc.task_instances[key] = max(
                acc.task_instances.get(key, 0), src_fence
            )
        # the source's workers never reattach here: requeue, don't hold
        acc.task_maybe_running[key] = False


def _drop_migrated_job(server, acc: _RestoreAcc, job_id: int,
                       to_shard: int) -> None:
    """migration-out-done replay: the handoff finalized before the crash.
    Only a tombstone survives, for wrong-shard redirects."""
    job = server.jobs.jobs.pop(job_id, None)
    if job is not None:
        for uid in job.streams:
            server._stream_jobs.pop(uid, None)
    acc.job_descs.pop(job_id, None)
    acc.lazy_chunks = [(j, s) for j, s in acc.lazy_chunks if j != job_id]
    for table in (acc.task_status, acc.task_finished_at,
                  acc.task_started_at, acc.task_instances,
                  acc.task_maybe_running, acc.task_variants,
                  acc.task_crashes):
        for key in [k for k in table if k[0] == job_id]:
            del table[key]
    acc.migrating_out.pop(job_id, None)
    acc.migrated_out[job_id] = to_shard


def _replay_record(server, acc: _RestoreAcc, record: dict) -> None:
    """One journal record into the accumulators (phase 2 / full replay)."""
    kind = record.get("event")
    job_id = record.get("job")
    # usage-ledger fold (ISSUE 18): the same observe() the live emit
    # path runs, on the same records in the same order — replay rebuilds
    # the ledger bit-equal to the crashed instance's. getattr: test
    # harnesses replay into bare fakes that carry no ledger
    ledger = getattr(server, "accounting", None)
    if ledger is not None:
        ledger.observe(kind, record)
    if kind == "job-submitted":
        desc = record.get("desc") or {}
        job = server.jobs.jobs.get(job_id)
        if job is None:
            job = server.jobs.create_job(
                name=desc.get("name", "job"),
                submit_dir=desc.get("submit_dir", "/"),
                max_fails=desc.get("max_fails"),
                is_open=desc.get("open", False),
                job_id=job_id,
            )
        submit_time = float(record.get("time", 0.0))
        if submit_time and (
            not job.tasks or submit_time < job.submitted_at
        ):
            job.submitted_at = submit_time
        # chunked-submit stream bookkeeping (ISSUE 10): applied chunk
        # indexes are the exactly-once fence a reconnecting client's
        # retried chunks are deduplicated against — restored for BOTH the
        # compact-lazy and the expanded replay paths below
        chunk = record.get("chunk")
        if isinstance(chunk, dict) and chunk.get("uid"):
            uid = chunk["uid"]
            server._stream_jobs[uid] = job_id
            stream = job.streams.get(uid)
            if stream is None:
                stream = job.streams[uid] = {
                    "applied": set(), "sealed": False,
                }
                job.open_streams += 1
            stream["applied"].add(int(chunk.get("i", 0)))
            if chunk.get("last") and not stream["sealed"]:
                stream["sealed"] = True
                job.open_streams = max(job.open_streams - 1, 0)
        array = desc.get("array")
        if array and _array_replays_lazy(server, array):
            # keep the array COMPACT through replay: it re-registers as a
            # lazy chunk at the end of restore (minus any journal-tail-
            # touched ids), exactly like a snapshot's "lazy" table — a
            # crash right after a 1M-task lazy submit must not make
            # restore O(tasks)
            id_range = array.get("id_range")
            n_array = (
                int(id_range[1]) - int(id_range[0])
                if id_range is not None else len(array["ids"])
            )
            spec: dict = {
                "request": array.get("request") or {},
                "body": array.get("body") or {},
                "priority": int(array.get("priority", 0)),
                "crash_limit": int(array.get("crash_limit", 5)),
                "submitted_at": submit_time,
                "ready_at": submit_time,
            }
            if id_range is not None:
                spec["id_range"] = [int(id_range[0]), int(id_range[1])]
            else:
                spec["ids"] = list(array["ids"])
            if array.get("entries") is not None:
                spec["entries"] = list(array["entries"])
            tctx0 = record.get("trace")
            if isinstance(tctx0, dict) and tctx0.get("id"):
                spec["trace"] = {**tctx0, "commit_at": submit_time}
            acc.lazy_chunks.append((job_id, spec))
            job.submits.append(submit_record(desc, n_array))
            return
        expanded = expand_desc_tasks(desc)
        for t in expanded:
            server.jobs.attach_task(job, t.get("id", 0))
            if submit_time:
                # keep the ORIGINAL submit clock, not the restore's
                job.tasks[t.get("id", 0)].submitted_at = submit_time
        if expanded:
            job.submits.append(submit_record(desc, len(expanded)))
        acc.job_descs.setdefault(job_id, []).extend(expanded)
        tctx = record.get("trace")
        if isinstance(tctx, dict) and tctx.get("id"):
            # per task, not per job: an open job accumulates submits, each
            # with its own trace id and clocks
            sub_trace = {**tctx, "commit_at": float(record.get("time", 0.0))}
            for t in expanded:
                acc.task_submit_trace[(job_id, t.get("id", 0))] = sub_trace
    elif kind == "job-opened":
        if job_id not in server.jobs.jobs:
            server.jobs.create_job(
                name=record.get("name", "job"),
                submit_dir=record.get("submit_dir", "/"),
                is_open=True,
                job_id=job_id,
            )
    elif kind == "job-closed":
        job = server.jobs.jobs.get(job_id)
        if job is not None:
            job.is_open = False
            # a close seals abandoned chunk streams (mirrors the live
            # _client_close_job), or the restored job could never end
            job.seal_streams()
    elif kind == "job-streams-sealed":
        # a forced seal (cancel / rejected chunk) — no `last` chunk event
        # exists for these, so the dedicated record re-seals on replay
        job = server.jobs.jobs.get(job_id)
        if job is not None:
            for uid in record.get("uids") or ():
                stream = job.streams.get(uid)
                if stream is not None and not stream["sealed"]:
                    stream["sealed"] = True
                    job.open_streams = max(job.open_streams - 1, 0)
    elif kind == "job-completed":
        job = server.jobs.jobs.get(job_id)
        if job is not None:
            if record.get("cancel_reason"):
                job.cancel_reason = record["cancel_reason"]
            # a job that reported completion has no open streams by
            # definition (belt and braces for pre-seal-event journals)
            job.seal_streams()
    elif kind in TERMINAL:
        acc.task_status[(job_id, record["task"])] = (
            TERMINAL[kind],
            record.get("error", ""),
        )
        acc.task_finished_at[(job_id, record["task"])] = float(
            record.get("time", 0.0)
        )
        tctx = record.get("trace")
        if isinstance(tctx, dict):
            acc.task_finish_wtrace[(job_id, record["task"])] = tctx
    elif kind == "task-started":
        key = (job_id, record["task"])
        acc.task_instances[key] = max(
            record.get("instance", 0), acc.task_instances.get(key, 0)
        )
        acc.task_variants[key] = record.get("variant", 0)
        acc.task_maybe_running[key] = True
        acc.task_started_at[key] = (
            float(record.get("queued_at", 0.0)),
            float(record.get("assigned_at", 0.0)),
            float(record.get("started_at", 0.0))
            or float(record.get("time", 0.0)),
        )
        tctx = record.get("trace")
        if isinstance(tctx, dict):
            wt = dict(tctx)
            wt["_worker"] = (record.get("workers") or [0])[0]
            wt["_instance"] = record.get("instance", 0)
            acc.task_wtrace[key] = wt
            lends = wt.get("lends")
            if lends is None:
                # legacy journals: scalar lent_from for the first worker
                lf = wt.get("lent_from")
                lends = ([[wt["_worker"], int(lf)]]
                         if lf is not None and int(lf) >= 0 else [])
            for lend_wid, home in lends:
                acc.task_lends.setdefault(key, []).append({
                    "worker": int(lend_wid),
                    "home_shard": int(home),
                    "instance": record.get("instance", 0),
                    "time": float(record.get("started_at", 0.0))
                    or float(record.get("time", 0.0)),
                })
    elif kind == "task-restarted":
        key = (job_id, record["task"])
        acc.task_crashes[key] = record.get(
            "crash_count", acc.task_crashes.get(key, 0)
        )
        acc.task_instances[key] = max(
            record.get("instance", 0), acc.task_instances.get(key, 0)
        )
        acc.task_maybe_running[key] = False
    elif kind == "server-uid":
        server.journal_uids.add(record.get("server_uid") or "")
        acc.n_boots += 1
    elif kind == "migration-out":
        # export sealed (ISSUE 17): the job stays here — held — until the
        # coordinator's re-driven migration commits or aborts the move
        acc.migrating_out[job_id] = dict(record)
    elif kind == "migration-in":
        _seed_migration_record(server, acc, record.get("record") or {})
    elif kind == "migration-out-done":
        _drop_migrated_job(server, acc, job_id, int(record.get("to", -1)))
    elif isinstance(kind, str) and kind.startswith("alloc-"):
        _replay_alloc_record(acc, kind, record)


def _apply_lazy_chunks(server, acc: _RestoreAcc) -> None:
    """Re-register a snapshot's unmaterialized array chunks (ISSUE 10).

    Each chunk re-enters the lazy store with its ORIGINAL clocks and
    interned request; ids that gained per-task state in the journal tail
    (started/crashed/terminal after the snapshot) are dropped from the
    chunk and appended to acc.job_descs so the standard per-task restore
    path (reattach holds, fencing, counters) handles them."""
    from hyperqueue_tpu.server.lazy import ArrayChunk

    touched: dict[int, set[int]] = {}
    for key in (
        set(acc.task_status)
        | set(acc.task_instances)
        | set(acc.task_maybe_running)
        | set(acc.task_crashes)
    ):
        touched.setdefault(key[0], set()).add(key[1])
    core = server.core
    for job_id, spec in acc.lazy_chunks:
        job = server.jobs.jobs.get(job_id)
        if job is None:
            continue
        rqv = rqv_from_wire(spec.get("request") or {}, core.resource_map)
        rq_id = core.intern_rqv(rqv)
        if "id_range" in spec:
            lo, hi = int(spec["id_range"][0]), int(spec["id_range"][1])
            id_range, ids = (lo, hi), None
            dead = [d for d in (spec.get("dead") or ()) if lo <= d < hi]
            contains = lambda t: lo <= t < hi  # noqa: E731
        else:
            ids = [int(t) for t in spec["ids"]]
            id_range = None
            dead = []
            id_set = set(ids)
            contains = lambda t: t in id_set  # noqa: E731
        hits = sorted(
            t for t in touched.get(job_id, ()) if contains(t)
        )
        chunk = ArrayChunk(
            job_id=job_id,
            rq_id=rq_id,
            priority=(int(spec.get("priority", 0)),
                      encode_sched_priority(job_id)),
            body=spec.get("body") or {},
            crash_limit=int(spec.get("crash_limit", 5)),
            id_range=id_range,
            ids=ids,
            entries=spec.get("entries"),
            submitted_at=float(spec.get("submitted_at") or 0.0),
            ready_at=float(spec.get("ready_at") or 0.0),
            trace=spec.get("trace"),
        )
        core.lazy.register(core, chunk)
        for t in dead:
            core.lazy.drop_id(core, job_id, t)
        descs = acc.job_descs.setdefault(job_id, [])
        for t in hits:
            if not core.lazy.drop_id(core, job_id, t):
                continue  # a dead id that also shows as touched
            server.jobs.attach_task(job, t)
            job.tasks[t].submitted_at = chunk.submitted_at
            if chunk.trace and chunk.trace.get("id"):
                # the chunk's submit stamps open this task's restored
                # trace, same as materialization would have
                acc.task_submit_trace.setdefault(
                    (job_id, t), dict(chunk.trace)
                )
            desc = {
                "id": t,
                "body": chunk.body,
                "request": spec.get("request") or {},
                "priority": chunk.priority[0],
                "crash_limit": chunk.crash_limit,
                "deps": (),
            }
            if chunk.entries is not None:
                index = chunk.index_of(t)
                if index is not None:
                    desc["entry"] = chunk.entries[index]
            descs.append(desc)
        if core.lazy.job_unmaterialized(job_id):
            server.comm.ask_for_scheduling()


def _rebuild_traces(server, acc: _RestoreAcc) -> None:
    """Reassemble the per-task trace store from what the journal (and/or
    snapshot) preserved, mirroring the spans the live EventBridge records.
    Span dedup on (name, instance) makes seeding + tail replay idempotent,
    so a snapshot-seeded trace merged with tail events stays ONE trace."""
    traces = server.core.traces
    if not traces.enabled:
        return
    for task_id, rec in acc.task_trace_seed.items():
        traces.seed(task_id, rec)
    keys = (
        set(acc.task_submit_trace)
        | set(acc.task_wtrace)
        | set(acc.task_finish_wtrace)
    )
    for key in sorted(keys):
        job_id, job_task_id = key
        task_id = make_task_id(job_id, job_task_id)
        sub = acc.task_submit_trace.get(key) or {}
        wt = acc.task_wtrace.get(key) or {}
        fin = acc.task_finish_wtrace.get(key) or {}
        trace_id = sub.get("id") or wt.get("id") or fin.get("id")
        if traces.get(task_id) is None:
            if not trace_id:
                continue
            traces.begin(task_id, trace_id)
        instance = wt.get("_instance", acc.task_instances.get(key, 0))
        wid = wt.get("_worker", 0)
        # fleet trace stitching (ISSUE 15): a start on a borrowed worker
        # journaled its lend context — rebuild the same annotation the
        # live EventBridge stamped (annotate() dedups against a snapshot-
        # seeded copy)
        for note in acc.task_lends.get(key, ()):
            traces.annotate(task_id, {
                "kind": "lend",
                "host_shard": getattr(server, "shard_id", 0),
                **note,
            })
        parent = None
        sent = float(sub.get("sent_at") or 0.0)
        recv = float(sub.get("recv_at") or 0.0)
        commit = float(sub.get("commit_at") or 0.0)
        if sent and recv:
            parent = traces.span(
                task_id, "client/submit", sent, recv, "client",
            ) or parent
        if recv and commit:
            parent = traces.span(
                task_id, "server/submit", recv, commit, "server",
                parent=parent,
            ) or parent
        stamps = acc.task_started_at.get(key)
        if stamps is not None:
            queued, assigned, _started = stamps
            if queued and assigned:
                parent = traces.span(
                    task_id, "server/queue", queued, assigned, "server",
                    instance, parent,
                ) or parent
            accepted = wt.get("accepted_at")
            if assigned and accepted:
                parent = traces.span(
                    task_id, "server/dispatch", assigned, accepted,
                    "server", instance, parent,
                ) or parent
            launch = wt.get("launch_at")
            if accepted and launch:
                parent = traces.span(
                    task_id, "worker/accept", accepted, launch,
                    f"worker:{wid}", instance, parent,
                ) or parent
            spawned = wt.get("spawned_at")
            if launch and spawned:
                parent = traces.span(
                    task_id, "worker/spawn", launch, spawned,
                    f"worker:{wid}", instance, parent,
                ) or parent
        if fin:
            terminal_at = acc.task_finished_at.get(key, 0.0)
            spawned = fin.get("spawned_at") or (
                stamps[2] if stamps else 0.0
            )
            exited = fin.get("exited_at")
            if spawned and exited:
                parent = traces.span(
                    task_id, "worker/run", spawned, exited,
                    f"worker:{wid}", instance, parent,
                ) or parent
            sent_up = fin.get("sent_at")
            if sent_up and terminal_at:
                parent = traces.span(
                    task_id, "worker/uplink", sent_up, terminal_at,
                    f"worker:{wid}", instance, parent,
                ) or parent
            if terminal_at:
                traces.span(
                    task_id, "server/commit", terminal_at, terminal_at,
                    "server", instance, parent,
                )
            traces.close(task_id)


def restore_from_journal(server) -> None:
    """Restore server.jobs/server.core from the snapshot + journal pair.

    Tasks that were RUNNING at the crash (a task-started with no terminal
    event — or, via a snapshot, RUNNING at capture with no later terminal)
    are held in server.reattach_pending instead of being requeued: their
    pre-crash worker keeps running them through the outage
    (`--on-server-lost reconnect`) and reclaims them at re-registration
    with the preserved instance id. Only when no worker reclaims a task
    within `--reattach-timeout` is it fenced (instance bump) and requeued
    (see Server._reattach_reaper). With the window disabled the fence +
    requeue happens here, the pre-reattach behavior.
    """
    t_restore0 = time.perf_counter()
    salvage = getattr(server, "journal_salvage", False)
    acc = _RestoreAcc()

    # --- phase 1: newest valid snapshot, with fallback -----------------
    watermark = None
    snap_used = None
    for state, snap_path in snapshot_mod.iter_snapshot_candidates(
        server.journal_path
    ):
        try:
            _seed_from_snapshot(server, acc, state)
            watermark = state["seq"]
            snap_used = snap_path
            break
        except Exception:
            logger.exception(
                "snapshot %s failed to load; falling back", snap_path
            )
            # the seed only touched jobs/seq/uids + accumulators: reset
            # them and try the next candidate (then full replay)
            server.jobs = JobManager()
            server.journal_uids = set()
            server._event_seq = 0
            server._stream_jobs = {}
            server.accounting.seed(None)
            acc = _RestoreAcc()

    # --- phase 2: journal tail replay ----------------------------------
    n_events = 0
    n_skipped = 0
    if server.journal_path.exists():
        for record in Journal.read_all(server.journal_path, salvage=salvage):
            # continue the event sequence where the journal left off so
            # stream-with-history seq dedup stays monotonic across restarts
            seq = record.get("seq")
            if isinstance(seq, int) and seq >= server._event_seq:
                server._event_seq = seq + 1
            if (
                watermark is not None
                and isinstance(seq, int)
                and seq < watermark
            ):
                # pre-watermark records survive GC only so that
                # `journal stream --history` keeps live jobs' timelines;
                # their effects are already inside the snapshot
                n_skipped += 1
                continue
            n_events += 1
            _replay_record(server, acc, record)

    # lazy snapshot chunks: ids the journal tail touched (a start, crash,
    # or terminal event after the snapshot) must materialize through the
    # normal per-task path; everything else re-registers as a lazy chunk —
    # a restored 1M-task lazy array stays O(chunks + touched)
    if acc.lazy_chunks:
        _apply_lazy_chunks(server, acc)

    # apply terminal statuses to job counters (with the ORIGINAL clock so
    # `hq job timeline` of a restored job reports true phase durations)
    for (job_id, task_id), (status, error) in acc.task_status.items():
        job = server.jobs.jobs.get(job_id)
        if job is None or task_id not in job.tasks:
            continue
        info = job.tasks[task_id]
        info.status = status
        info.error = error
        info.finished_at = acc.task_finished_at.get((job_id, task_id), 0.0)
        stamps = acc.task_started_at.get((job_id, task_id))
        if stamps is not None:
            info.started_at = stamps[2]
        job.counters[status] += 1

    # re-submit unfinished tasks into the core
    from hyperqueue_tpu.server.task import INSTANCE_GENERATION_STRIDE

    fence_floor = max(acc.n_boots, 1) * INSTANCE_GENERATION_STRIDE
    server.core.instance_fence_floor = fence_floor
    server.n_boots = acc.n_boots
    resubmitted = 0
    held = 0
    reattach_window = getattr(server, "reattach_timeout", 0.0)
    reattach_deadline = clock.monotonic() + reattach_window
    for job_id, descs in acc.job_descs.items():
        job = server.jobs.jobs.get(job_id)
        if job is None:
            continue
        new_tasks = []
        for t in descs:
            job_task_id = t.get("id", 0)
            key = (job_id, job_task_id)
            if key in acc.task_status:
                continue  # already terminal
            rqv = rqv_from_wire(t.get("request") or {}, server.core.resource_map)
            rq_id = server.core.intern_rqv(rqv)
            deps = tuple(
                make_task_id(job_id, d)
                for d in t.get("deps", ())
                if acc.task_status.get((job_id, d), ("",))[0] != "finished"
            )
            # failed/canceled dependency => this task can never run; mark it
            dead_dep = any(
                acc.task_status.get((job_id, d), ("",))[0]
                in ("failed", "canceled")
                for d in t.get("deps", ())
            )
            if dead_dep:
                job.tasks[job_task_id].status = "canceled"
                job.counters["canceled"] += 1
                continue
            task = Task(
                task_id=make_task_id(job_id, job_task_id),
                rq_id=rq_id,
                priority=(int(t.get("priority", 0)),
                          encode_sched_priority(job_id)),
                body=t.get("body", {}),
                entry=t.get("entry"),
                deps=deps,
                crash_limit=int(t.get("crash_limit", 5)),
            )
            task.crash_counter = acc.task_crashes.get(key, 0)
            started_instance = acc.task_instances.get(key)
            if started_instance is None:
                # never started AS FAR AS THE JOURNAL KNOWS. The start —
                # or a whole start/requeue/restart chain — may sit in the
                # crashed boot's lost tail (worker uplink coalescing + the
                # in-flight journal batch) while an incarnation still runs
                # on a reconnecting worker; re-issuing at an id that chain
                # reached would execute one instance twice, invisible to
                # the (task, instance) equality fence. Jumping to this
                # boot's generation base clears every id any prior boot
                # could have issued; the reconnecting worker's stale claim
                # is then discarded and its copy killed at re-registration.
                task.fence_instance(fence_floor)
                new_tasks.append(task)
                continue
            # preserved instance id: stale pre-crash worker messages carry
            # older instance ids and are dropped (reference gateway.rs:204
            # adjust_instance_id_and_crash_counters)
            task.instance_id = started_instance
            task.assigned_variant = acc.task_variants.get(key, 0)
            if (
                reattach_window > 0
                and acc.task_maybe_running.get(key)
                and not rqv.is_multi_node
            ):
                # maybe still running on a reconnecting worker: hold it out
                # of the queues (state WAITING, deps all finished) until a
                # worker reclaims it or the window expires. Gangs are never
                # held — a partial gang reattach is worthless, so they are
                # fenced + requeued like before.
                stamps = acc.task_started_at.get(key)
                if stamps is not None:
                    # pre-seed the lifecycle chain from the journal: on
                    # reattach the task keeps its ORIGINAL start (one
                    # unbroken timeline, no duplicate spawn phase)
                    task.t_ready, task.t_assigned, task.t_started = stamps
                server.core.tasks[task.task_id] = task
                server.reattach_pending[task.task_id] = reattach_deadline
                held += 1
            else:
                # fence out the pre-crash incarnation (and anything past
                # it in the lost tail) and requeue now
                task.fence_instance(fence_floor)
                new_tasks.append(task)
        if new_tasks:
            reactor.on_new_tasks(server.core, server.comm, new_tasks)
            resubmitted += len(new_tasks)

    # elastic resharding (ISSUE 17): restore the handoff tombstones and
    # re-seal jobs whose export had no journaled finalize — they stay
    # paused until the coordinator re-drives (or aborts) the migration
    server.migrated_out.update(acc.migrated_out)
    if acc.migrating_out:
        server.migrating_out.update(acc.migrating_out)
        reactor.pause_jobs(server.core, server.comm,
                           list(acc.migrating_out))
    _rebuild_traces(server, acc)

    # hand the reconstructed allocation table to the autoalloc service
    # (created after restore in Server.start): restored active allocations
    # are reconciled against the manager on the first refresh — never
    # double-submitted, never leaked — and unresolved submit attempts are
    # pidfile-scanned for orphans
    if acc.autoalloc or acc.alloc_attempts:
        queues_out = []
        for q in acc.autoalloc.values():
            qd = dict(q)
            qd["allocations"] = list(qd.pop("_allocs", {}).values())
            queues_out.append(qd)
        server.restored_autoalloc = {
            "queues": queues_out,
            "next_queue_id": acc.next_alloc_queue_id,
            "attempts": acc.alloc_attempts,
        }
    duration = time.perf_counter() - t_restore0
    _RESTORE_SECONDS.observe(duration)
    server.last_restore = {
        "duration_s": round(duration, 4),
        "snapshot": str(snap_used) if snap_used else None,
        "tail_events": n_events,
        "skipped_pre_watermark": n_skipped,
        "jobs": len(server.jobs.jobs),
        "resubmitted": resubmitted,
        "held_for_reattach": held,
    }
    logger.info(
        "restored %d jobs in %.3fs (%s, %d tail events, %d tasks "
        "resubmitted, %d held for reattach) from %s",
        len(server.jobs.jobs),
        duration,
        f"snapshot {snap_used.name}" if snap_used else "full replay",
        n_events,
        resubmitted,
        held,
        server.journal_path,
    )
