"""Output streaming: task stdout/stderr multiplexed into per-worker log files.

Reference: crates/hyperqueue/src/worker/streamer.rs (worker side: chunks of
task stdout/stderr appended to `<dir>/<uid>.hqs`, header `hqsf0000`) and
crates/hyperqueue/src/stream/reader/outputlog.rs (reader: merge files, index
by task/instance/channel, superseded-instance filtering; CLI `hq output-log
{summary,cat,show,export}`).

Format here: header magic "hqtpusf1", then msgpack records
{t: task_id, i: instance, c: 0|1 (stdout|stderr), d: bytes} with u32-LE
length prefixes. A `close` record (c: 2) marks a task's stream complete.
"""

from __future__ import annotations

import struct
from pathlib import Path

import msgpack

MAGIC = b"hqtpusf1"
_LEN = struct.Struct("<I")

STDOUT = 0
STDERR = 1
CLOSE = 2


class StreamWriter:
    """Worker-side appender; one per (worker, stream dir)."""

    def __init__(self, directory: str | Path, worker_id: int, server_uid: str):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.path = self.dir / f"{server_uid}.{worker_id}.hqs"
        fresh = not self.path.exists()
        self._file = open(self.path, "ab")
        if fresh:
            self._file.write(MAGIC)
            self._file.flush()

    def write_chunk(self, task_id: int, instance: int, channel: int,
                    data: bytes) -> None:
        record = msgpack.packb(
            {"t": task_id, "i": instance, "c": channel, "d": data},
            use_bin_type=True,
        )
        self._file.write(_LEN.pack(len(record)) + record)
        self._file.flush()

    def close_task(self, task_id: int, instance: int) -> None:
        self.write_chunk(task_id, instance, CLOSE, b"")

    def close(self) -> None:
        self._file.close()


class OutputLog:
    """Reader over all .hqs files in a stream directory."""

    def __init__(self, directory: str | Path):
        self.dir = Path(directory)
        # task_id -> instance -> channel -> [bytes]
        self.chunks: dict[int, dict[int, dict[int, list[bytes]]]] = {}
        self.closed: set[tuple[int, int]] = set()
        for path in sorted(self.dir.glob("*.hqs")):
            self._read_file(path)

    def _read_file(self, path: Path) -> None:
        with open(path, "rb") as f:
            if f.read(len(MAGIC)) != MAGIC:
                return
            while True:
                header = f.read(_LEN.size)
                if len(header) < _LEN.size:
                    return
                (length,) = _LEN.unpack(header)
                payload = f.read(length)
                if len(payload) < length:
                    return
                rec = msgpack.unpackb(payload, raw=False, strict_map_key=False)
                task, inst, chan = rec["t"], rec["i"], rec["c"]
                if chan == CLOSE:
                    self.closed.add((task, inst))
                    continue
                self.chunks.setdefault(task, {}).setdefault(inst, {}) \
                    .setdefault(chan, []).append(rec["d"])

    def _live_instance(self, task_id: int) -> int | None:
        """Highest instance wins; superseded instances are filtered
        (reference outputlog.rs superseded-instance logic)."""
        instances = self.chunks.get(task_id)
        if not instances:
            return None
        return max(instances)

    def task_ids(self) -> list[int]:
        return sorted(self.chunks)

    def job_ids(self) -> list[int]:
        """Job ids present in the stream dir (reference outputlog.rs:349
        `jobs()` — prints the index's job keys)."""
        from hyperqueue_tpu.ids import task_id_job

        return sorted({task_id_job(t) for t in self.chunks})

    def cat(self, task_id: int, channel: int) -> bytes:
        inst = self._live_instance(task_id)
        if inst is None:
            return b""
        return b"".join(self.chunks[task_id][inst].get(channel, []))

    def summary(self) -> dict:
        n_chunks = 0
        n_bytes = 0
        for instances in self.chunks.values():
            for channels in instances.values():
                for chunk_list in channels.values():
                    n_chunks += len(chunk_list)
                    n_bytes += sum(len(c) for c in chunk_list)
        return {
            "files": len(list(self.dir.glob("*.hqs"))),
            "tasks": len(self.chunks),
            "chunks": n_chunks,
            "bytes": n_bytes,
            "closed_streams": len(self.closed),
        }

    def export(self):
        """Yield {task, instance, channel, data} dicts (NDJSON-able)."""
        from hyperqueue_tpu.ids import task_id_job, task_id_task

        for task_id in self.task_ids():
            inst = self._live_instance(task_id)
            for chan in (STDOUT, STDERR):
                data = b"".join(self.chunks[task_id][inst].get(chan, []))
                if data:
                    yield {
                        "job": task_id_job(task_id),
                        "task": task_id_task(task_id),
                        "instance": inst,
                        "channel": "stdout" if chan == STDOUT else "stderr",
                        "data": data.decode(errors="replace"),
                    }
