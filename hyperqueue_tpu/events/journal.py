"""Append-only event journal with torn-tail tolerance.

Reference: crates/hyperqueue/src/server/event/journal/ — header-versioned
append-only file of serialized events (`hqjl0002`, write.rs:12-76), flushed
periodically and synchronously after client-visible mutations; a torn tail
(crash mid-write) is detected and truncated on restore (read.rs:60); pruning
rewrites the file dropping completed jobs (prune.rs).

Format here: 8-byte magic "hqtpujl1", then records of [u32-LE length][msgpack
payload].
"""

from __future__ import annotations

import os
import struct
import time
from pathlib import Path

import msgpack

from hyperqueue_tpu.utils.metrics import REGISTRY

MAGIC = b"hqtpujl1"
_LEN = struct.Struct("<I")

# fsync stalls are the journal's dominant latency risk (--journal-fsync
# always puts one on every event); the histogram makes a slow disk visible
# on the metrics plane instead of as mystery event-loop hiccups
_FSYNC_SECONDS = REGISTRY.histogram(
    "hq_journal_fsync_seconds", "journal fsync latency"
)
_WRITES_TOTAL = REGISTRY.counter(
    "hq_journal_writes_total", "journal records appended"
)
_BYTES_TOTAL = REGISTRY.counter(
    "hq_journal_bytes_total", "journal payload bytes appended"
)


class Journal:
    def __init__(self, path: Path):
        self.path = Path(path)
        self._file = None
        # group-commit buffer: while a batch is open, framed records
        # accumulate here and hit the file as ONE write at commit — the
        # completion plane's per-batch cost is one os.write (+ one fsync
        # under --journal-fsync always) instead of one per task event
        self._batch: list[bytes] | None = None

    def open_for_append(self) -> None:
        exists = self.path.exists() and self.path.stat().st_size >= len(MAGIC)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        if exists:
            # drop a torn tail before appending
            valid_end = self._scan_valid_end()
            self._file = open(self.path, "r+b")
            self._file.truncate(valid_end)
            self._file.seek(valid_end)
        else:
            self._file = open(self.path, "wb")
            self._file.write(MAGIC)
            self._file.flush()

    def _scan_valid_end(self) -> int:
        with open(self.path, "rb") as f:
            if f.read(len(MAGIC)) != MAGIC:
                raise ValueError(f"{self.path} is not a journal file")
            pos = len(MAGIC)
            while True:
                header = f.read(_LEN.size)
                if len(header) < _LEN.size:
                    return pos
                (length,) = _LEN.unpack(header)
                payload = f.read(length)
                if len(payload) < length:
                    return pos
                pos = f.tell()

    def write(self, record: dict) -> None:
        data = msgpack.packb(record, use_bin_type=True)
        framed = _LEN.pack(len(data)) + data
        if self._batch is not None:
            self._batch.append(framed)
        else:
            self._file.write(framed)
        _WRITES_TOTAL.inc()
        _BYTES_TOTAL.inc(len(data))

    @property
    def in_batch(self) -> bool:
        """True while a group-commit batch is open (writes are buffered)."""
        return self._batch is not None

    def begin_batch(self) -> None:
        """Buffer subsequent writes until commit_batch (idempotent)."""
        if self._batch is None:
            self._batch = []

    def commit_batch(self) -> int:
        """Write the buffered batch as one append; returns records written.
        The batch is closed either way — callers decide the flush/fsync."""
        buf, self._batch = self._batch, None
        if not buf:
            return 0
        self._file.write(b"".join(buf))
        return len(buf)

    def flush(self, sync: bool = False) -> None:
        if self._file is not None:
            if self._batch:
                # a flush demanded mid-batch (explicit `hq journal flush`,
                # history replay) must see every written record on disk
                buf, self._batch = self._batch, []
                self._file.write(b"".join(buf))
            self._file.flush()
            if sync:
                t0 = time.perf_counter()
                os.fsync(self._file.fileno())
                _FSYNC_SECONDS.observe(time.perf_counter() - t0)

    def close(self) -> None:
        if self._file is not None:
            if self._batch:
                self.commit_batch()
            self._batch = None
            self.flush(sync=True)
            self._file.close()
            self._file = None

    @staticmethod
    def read_all(path: Path):
        """Yield records, silently stopping at a torn tail (reference
        read.rs:109-235 tests this tolerance)."""
        with open(path, "rb") as f:
            if f.read(len(MAGIC)) != MAGIC:
                raise ValueError(f"{path} is not a journal file")
            while True:
                header = f.read(_LEN.size)
                if len(header) < _LEN.size:
                    return
                (length,) = _LEN.unpack(header)
                payload = f.read(length)
                if len(payload) < length:
                    return
                try:
                    yield msgpack.unpackb(payload, raw=False)
                except Exception:
                    return

    @staticmethod
    def prune(path: Path, keep_jobs: set[int]) -> int:
        """Rewrite the journal keeping only events of `keep_jobs` (live jobs);
        worker lifecycle events are dropped. Returns records kept."""
        tmp = Path(str(path) + ".prune")
        kept = 0
        with open(tmp, "wb") as out:
            out.write(MAGIC)
            for record in Journal.read_all(path):
                job = record.get("job")
                if job is not None and job not in keep_jobs:
                    continue
                if job is None:
                    # worker/overview events are not restorable state — but
                    # the server-uid lineage records must survive, or a
                    # post-prune restore could never verify reattach claims
                    if record.get("event") != "server-uid":
                        continue
                data = msgpack.packb(record, use_bin_type=True)
                out.write(_LEN.pack(len(data)) + data)
                kept += 1
            out.flush()
            os.fsync(out.fileno())
        tmp.replace(path)
        return kept
