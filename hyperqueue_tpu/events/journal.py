"""Append-only event journal with torn-tail tolerance and per-record CRCs.

Reference: crates/hyperqueue/src/server/event/journal/ — header-versioned
append-only file of serialized events (`hqjl0002`, write.rs:12-76), flushed
periodically and synchronously after client-visible mutations; a torn tail
(crash mid-write) is detected and truncated on restore (read.rs:60); pruning
rewrites the file dropping completed jobs (prune.rs).

Format here (v2, magic "hqtpujl2"): 8-byte magic, then records of
[u32-LE length][u32-LE CRC32 of payload][msgpack payload]. v1 files
(magic "hqtpujl1", no CRC) are read transparently; any rewrite (prune,
compaction GC) upgrades to v2.

The CRC lets the reader tell two very different failures apart:

- **torn tail** — a crash mid-write left an incomplete (or CRC-bad) final
  record at EOF. Expected under kill -9; silently truncated.
- **mid-file corruption** — a complete record whose CRC does not match,
  with more records after it (bit rot, partial sector writes). NOT a crash
  artifact: raises `JournalCorruption` loudly. `salvage=True`
  (`hq server start --journal-salvage`) skips such records instead,
  counting them in `hq_journal_salvaged_records_total`.
"""

from __future__ import annotations

import logging
import os
import struct
import time
import zlib
from pathlib import Path

import msgpack

from hyperqueue_tpu.utils.metrics import REGISTRY

MAGIC = b"hqtpujl2"
MAGIC_V1 = b"hqtpujl1"
_LEN = struct.Struct("<I")
_LEN_CRC = struct.Struct("<II")

logger = logging.getLogger("hq.journal")

# fsync stalls are the journal's dominant latency risk (--journal-fsync
# always puts one on every event); the histogram makes a slow disk visible
# on the metrics plane instead of as mystery event-loop hiccups
_FSYNC_SECONDS = REGISTRY.histogram(
    "hq_journal_fsync_seconds", "journal fsync latency"
)
_WRITES_TOTAL = REGISTRY.counter(
    "hq_journal_writes_total", "journal records appended"
)
_BYTES_TOTAL = REGISTRY.counter(
    "hq_journal_bytes_total", "journal payload bytes appended"
)
_SALVAGED_TOTAL = REGISTRY.counter(
    "hq_journal_salvaged_records_total",
    "corrupt mid-file journal records skipped in salvage mode",
)


class JournalCorruption(RuntimeError):
    """A complete journal record failed its CRC (or decode) mid-file.

    Distinct from a torn tail: a torn tail is the expected artifact of a
    crash mid-append and is silently truncated; mid-file corruption means
    the bytes on disk changed after they were written."""


def fsync_dir(path: Path) -> None:
    """fsync a directory so a rename inside it is durable.

    `os.replace` alone is NOT crash-durable: the rename lives in the
    directory, and a crash before the directory metadata reaches disk can
    resurrect the old file. Every atomic-rename in the durability layer
    (snapshot publish, prune, compaction GC swap) must be followed by
    this."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _frame(data: bytes, version: int) -> bytes:
    if version >= 2:
        return _LEN_CRC.pack(len(data), zlib.crc32(data)) + data
    return _LEN.pack(len(data)) + data


def _sniff_version(path: Path) -> int:
    with open(path, "rb") as f:
        head = f.read(len(MAGIC))
    if head == MAGIC:
        return 2
    if head == MAGIC_V1:
        return 1
    raise ValueError(f"{path} is not a journal file")


def _read_frames(f, version: int, path, salvage: bool, stop_at=None):
    """Yield (payload, record_start) for complete, CRC-valid frames.

    Stops (torn tail) when the final frame is incomplete or — v2 — its CRC
    fails AND it extends to EOF/stop_at. A CRC failure with more data after
    it is mid-file corruption: raise JournalCorruption, or with `salvage`
    skip the record, bump the salvage counter, and keep going (the framing
    itself is intact, so the next record is findable)."""
    header_struct = _LEN_CRC if version >= 2 else _LEN
    end = stop_at
    while True:
        start = f.tell()
        if end is not None and start >= end:
            return
        header = f.read(header_struct.size)
        if len(header) < header_struct.size:
            return  # torn tail: incomplete header
        if version >= 2:
            length, crc = header_struct.unpack(header)
        else:
            (length,) = header_struct.unpack(header)
            crc = None
        payload = f.read(length)
        if len(payload) < length:
            return  # torn tail: incomplete payload
        if crc is not None and zlib.crc32(payload) != crc:
            record_end = f.tell()
            f.seek(0, os.SEEK_END)
            file_end = f.tell()
            f.seek(record_end)
            if record_end >= (end if end is not None else file_end):
                # the bad record is the last thing in the file: a partial
                # sector write at the crash point, i.e. a torn tail
                return
            if not salvage:
                raise JournalCorruption(
                    f"{path}: record at byte {start} failed its CRC with "
                    f"{file_end - record_end} bytes of journal after it — "
                    "mid-file corruption, not a torn tail (re-run with "
                    "--journal-salvage to skip bad records)"
                )
            _SALVAGED_TOTAL.inc()
            logger.error(
                "salvage: skipping corrupt journal record at byte %d of %s",
                start, path,
            )
            continue
        yield payload, start


class Journal:
    def __init__(self, path: Path, salvage: bool = False):
        self.path = Path(path)
        self.salvage = salvage
        self._file = None
        self._version = 2
        # group-commit buffer: while a batch is open, framed records
        # accumulate here and hit the file as ONE write at commit — the
        # completion plane's per-batch cost is one os.write (+ one fsync
        # under --journal-fsync always) instead of one per task event
        self._batch: list[bytes] | None = None

    def open_for_append(self) -> None:
        exists = self.path.exists() and self.path.stat().st_size >= len(MAGIC)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        if exists:
            # an existing file keeps its framing version (mixed framing in
            # one file would be unreadable); rewrites upgrade to v2
            self._version = _sniff_version(self.path)
            # drop a torn tail before appending
            valid_end = self._scan_valid_end()
            self._file = open(self.path, "r+b")
            self._file.truncate(valid_end)
            self._file.seek(valid_end)
        else:
            self._version = 2
            self._file = open(self.path, "wb")
            self._file.write(MAGIC)
            self._file.flush()

    def _scan_valid_end(self) -> int:
        with open(self.path, "rb") as f:
            f.seek(len(MAGIC))
            pos = len(MAGIC)
            for _payload, _start in _read_frames(
                f, self._version, self.path, self.salvage
            ):
                pos = f.tell()
            return pos

    def write(self, record: dict) -> None:
        data = msgpack.packb(record, use_bin_type=True)
        framed = _frame(data, self._version)
        if self._batch is not None:
            self._batch.append(framed)
        else:
            self._file.write(framed)
        _WRITES_TOTAL.inc()
        _BYTES_TOTAL.inc(len(data))

    @property
    def in_batch(self) -> bool:
        """True while a group-commit batch is open (writes are buffered)."""
        return self._batch is not None

    def begin_batch(self) -> None:
        """Buffer subsequent writes until commit_batch (idempotent)."""
        if self._batch is None:
            self._batch = []

    def commit_batch(self) -> int:
        """Write the buffered batch as one append; returns records written.
        The batch is closed either way — callers decide the flush/fsync."""
        buf, self._batch = self._batch, None
        if not buf:
            return 0
        self._file.write(b"".join(buf))
        return len(buf)

    def flush(self, sync: bool = False) -> None:
        if self._file is not None:
            if self._batch:
                # a flush demanded mid-batch (explicit `hq journal flush`,
                # history replay) must see every written record on disk
                buf, self._batch = self._batch, []
                self._file.write(b"".join(buf))
            self._file.flush()
            if sync:
                t0 = time.perf_counter()
                os.fsync(self._file.fileno())
                _FSYNC_SECONDS.observe(time.perf_counter() - t0)

    def close(self) -> None:
        if self._file is not None:
            if self._batch:
                self.commit_batch()
            self._batch = None
            self.flush(sync=True)
            self._file.close()
            self._file = None

    def kill(self) -> None:
        """Abandon the appender with kill -9 semantics: whatever the flush
        policy already pushed to the OS stays on disk, everything still in
        the user-space buffer (an open group-commit batch, bytes the
        BufferedWriter holds) is LOST — exactly what a SIGKILL of the
        process would leave behind.  The deterministic simulator uses this
        to model server death without ending the test process.

        The buffered file object cannot simply be dropped (Python flushes
        on finalize, which would resurrect the "lost" tail — possibly
        AFTER a restored appender wrote past it) nor os.close()d (the fd
        number could be reused before the finalizer runs and the flush
        would land in an unrelated file).  Redirecting the fd to /dev/null
        makes the eventual flush+close harmless and exact."""
        if self._file is None:
            return
        self._batch = None
        devnull = os.open(os.devnull, os.O_WRONLY)
        try:
            os.dup2(devnull, self._file.fileno())
        finally:
            os.close(devnull)
        try:
            self._file.close()  # flushes the doomed buffer into /dev/null
        except OSError:
            pass
        self._file = None

    @staticmethod
    def read_all(path: Path, salvage: bool = False):
        """Yield records, silently stopping at a torn tail (reference
        read.rs:109-235 tests this tolerance). Mid-file corruption raises
        JournalCorruption unless `salvage` (see module docstring)."""
        version = _sniff_version(path)
        with open(path, "rb") as f:
            f.seek(len(MAGIC))
            for payload, start in _read_frames(f, version, path, salvage):
                try:
                    yield msgpack.unpackb(payload, raw=False)
                except Exception:
                    if version < 2:
                        # v1 has no CRC: an undecodable record is
                        # indistinguishable from a torn tail — keep the
                        # legacy stop-here tolerance
                        return
                    # v2: the CRC matched but msgpack failed — the record
                    # was written broken; same policy as a CRC failure
                    if not salvage:
                        raise JournalCorruption(
                            f"{path}: CRC-valid record at byte {start} "
                            "failed to decode"
                        )
                    _SALVAGED_TOTAL.inc()
                    logger.error(
                        "salvage: skipping undecodable journal record at "
                        "byte %d of %s", start, path,
                    )

    @staticmethod
    def prune(path: Path, keep_jobs: set[int], salvage: bool = False) -> int:
        """Rewrite the journal keeping only events of `keep_jobs` (live jobs);
        worker lifecycle events are dropped. Returns records kept."""
        tmp = Path(str(path) + ".prune")
        kept = 0
        with open(tmp, "wb") as out:
            out.write(MAGIC)  # rewrites always upgrade to v2 framing
            for record in Journal.read_all(path, salvage=salvage):
                job = record.get("job")
                if job is not None and job not in keep_jobs:
                    continue
                if job is None:
                    # worker/overview events are not restorable state — but
                    # the server-uid lineage records must survive, or a
                    # post-prune restore could never verify reattach claims
                    if record.get("event") != "server-uid":
                        continue
                data = msgpack.packb(record, use_bin_type=True)
                out.write(_frame(data, 2))
                kept += 1
            out.flush()
            os.fsync(out.fileno())
        tmp.replace(path)
        # without this, a crash after the rename can resurrect the
        # pre-prune journal — the rename lives in directory metadata
        fsync_dir(path.parent)
        return kept

    @staticmethod
    def gc_rewrite(
        path: Path,
        tmp: Path,
        keep_jobs: set[int],
        watermark: int,
        stop_at: int,
        salvage: bool = False,
    ) -> tuple[int, int]:
        """Compaction GC: rewrite the pre-snapshot region [magic, stop_at)
        into `tmp`, dropping events already superseded by the snapshot.

        Kept: records of still-live jobs (so `journal stream --history`
        keeps their timeline), server-uid lineage records (so a fallback
        full replay still fences instance generations), and — defensively —
        anything at/after the snapshot seq watermark. Dropped: completed/
        forgotten jobs' events and worker lifecycle noise, all of which the
        snapshot carries in O(live-state) form.

        Runs against a live appender: only bytes below `stop_at` (the file
        size at the compaction barrier) are read, so concurrent appends are
        invisible here and are carried over by `finalize` afterwards.
        Output is always v2 framing. Returns (kept, dropped)."""
        from hyperqueue_tpu.utils import chaos

        version = _sniff_version(path)
        kept = dropped = 0
        with open(path, "rb") as src, open(tmp, "wb") as out:
            out.write(MAGIC)
            src.seek(len(MAGIC))
            for payload, _start in _read_frames(
                src, version, path, salvage, stop_at=stop_at
            ):
                try:
                    record = msgpack.unpackb(payload, raw=False)
                except Exception:
                    if version >= 2 and not salvage:
                        raise JournalCorruption(
                            f"{path}: undecodable record during compaction"
                        )
                    dropped += 1
                    continue
                seq = record.get("seq")
                job = record.get("job")
                keep = (
                    (isinstance(seq, int) and seq >= watermark)
                    or (job is not None and job in keep_jobs)
                    or record.get("event") == "server-uid"
                )
                if not keep:
                    dropped += 1
                    continue
                out.write(_frame(payload, 2))
                kept += 1
                if chaos.ACTIVE:
                    chaos.fire("server.compact", event="mid-gc")
            out.flush()
            os.fsync(out.fileno())
        return kept, dropped

    @staticmethod
    def gc_finalize(path: Path, tmp: Path, stop_at: int) -> None:
        """Carry the frames appended after `stop_at` (events that arrived
        during the GC rewrite) onto `tmp`, then atomically publish `tmp` as
        the journal. The caller must have closed/quiesced the appender: the
        open handle would keep writing to the replaced inode otherwise."""
        version = _sniff_version(path)
        with open(path, "rb") as src, open(tmp, "r+b") as out:
            out.seek(0, os.SEEK_END)
            src.seek(stop_at)
            # re-frame rather than raw-copy: the tail may be v1 framing
            # while tmp is always v2
            for payload, _start in _read_frames(src, version, path, True):
                out.write(_frame(payload, 2))
            out.flush()
            os.fsync(out.fileno())
        tmp.replace(path)
        fsync_dir(path.parent)
