"""Server-state snapshots: the O(live-state) half of journal compaction.

Reference: crates/hyperqueue/src/server/event/journal/prune.rs bounds the
journal by rewriting it; here the bound is stronger — a snapshot captures
the full restorable server state (jobs, task statuses and counters, open
submits, instance-fence lineage, the event-seq watermark) so restore can
load it and replay only the post-snapshot journal tail instead of every
event ever written. Restore time and memory become O(live state), not
O(history).

File format (`<journal>.snap`, fallback `<journal>.snap.prev`):

    8-byte magic "hqtpusn1" | u32-LE payload length | msgpack payload
    | u32-LE CRC32 of payload

Durability contract: the snapshot is written to a temp file, fsynced,
published with an atomic rename, and the parent directory is fsynced —
only then may the journal GC drop anything the snapshot covers. The
previous snapshot is rotated to `.snap.prev` first, so a torn/corrupt
newest snapshot falls back to the previous one, and from there to a full
journal replay. A crash at ANY point leaves at least one restorable
source (chaos-tested in tests/test_snapshot.py).

The payload is deliberately shaped like the journal-replay accumulators in
events/restore.py: loading a snapshot seeds exactly the state a full
replay of the pre-watermark journal would have produced (property-tested
bit-equal), so every restore invariant — reattach holds, original
timeline clocks, generation-base fencing — is preserved by construction.
"""

from __future__ import annotations

import logging
import os
import struct
import time
import zlib
from pathlib import Path

import msgpack

from hyperqueue_tpu.events.journal import fsync_dir
from hyperqueue_tpu.ids import make_task_id, task_id_task
from hyperqueue_tpu.utils import chaos
from hyperqueue_tpu.utils import clock

MAGIC = b"hqtpusn1"
VERSION = 1
_LEN = struct.Struct("<I")

logger = logging.getLogger("hq.snapshot")

_TERMINAL = ("finished", "failed", "canceled")


class SnapshotError(RuntimeError):
    """The snapshot file is torn, corrupt, or from an unknown version."""


def snapshot_path(journal_path: Path) -> Path:
    return Path(str(journal_path) + ".snap")


def prev_snapshot_path(journal_path: Path) -> Path:
    return Path(str(journal_path) + ".snap.prev")


def have_snapshot(journal_path: Path) -> bool:
    return (
        snapshot_path(journal_path).exists()
        or prev_snapshot_path(journal_path).exists()
    )


# --------------------------------------------------------------------------
# capture: live server state -> snapshot payload
# --------------------------------------------------------------------------
def capture_state(server) -> dict:
    """Serialize the server's restorable state as of NOW.

    Must run synchronously on the reactor loop (no awaits between the
    event-seq watermark read and the last field captured): the watermark
    asserts "everything below this seq is inside", which is only true
    while no handler can interleave.

    Task bodies and resource requests are deduped through shared tables:
    an array's tasks share ONE body object in the core, and the snapshot
    preserves that sharing (the wire-level body dedup relies on identity,
    see protocol.expand_desc_tasks) while keeping the payload O(live
    state) rather than O(tasks x body size).
    """
    from hyperqueue_tpu.server.protocol import rqv_to_wire
    from hyperqueue_tpu.server.task import TaskState

    bodies: list[dict] = []
    body_index: dict[int, int] = {}
    requests: list[dict] = []
    request_index: dict[int, int] = {}
    jobs_out = [
        capture_job(server, job, bodies, body_index, requests, request_index)
        for job in server.jobs.jobs.values()
    ]
    # live tasks' distributed traces (utils/trace.py TaskTraceStore): the
    # GC'd journal prefix held their submit/start events, so the snapshot
    # must carry the assembled spans or a snapshot-seeded restore would
    # break the "one unbroken trace across restart" contract. Terminal
    # tasks are excluded — bounded by live state like everything else here.
    live_task_ids = [
        make_task_id(jd["id"], t["id"])
        for jd in jobs_out
        for t in jd["pending"]
    ]
    # allocation table (ISSUE 13): queues + allocation lifecycle + submits
    # in flight, so a snapshot-seeded restore reconciles the live
    # allocation set against the manager instead of forgetting it
    autoalloc = getattr(server, "autoalloc", None)
    return {
        "version": VERSION,
        "time": clock.now(),
        "autoalloc": autoalloc.capture() if autoalloc is not None else None,
        "traces": server.core.traces.snapshot_live(live_task_ids),
        # event-seq watermark: every event with seq < this is folded into
        # the snapshot; restore replays only seq >= this from the journal
        "seq": server._event_seq,
        # server-uid records written up to the watermark (this boot
        # included): the next restore's instance-generation fence base
        "n_boots": server.n_boots,
        "server_uids": sorted(server.journal_uids),
        "next_job_id": server.jobs.job_id_counter.peek(),
        # usage ledger as of the SAME watermark (ISSUE 18): capture runs
        # synchronously between emits, so the captured rows correspond
        # exactly to the events with seq < watermark — a snapshot-seeded
        # restore is bit-equal to a full replay. Optional on read:
        # pre-accounting snapshots seed an empty ledger.
        "accounting": (
            server.accounting.capture()
            if getattr(server, "accounting", None) is not None
            else None
        ),
        "bodies": bodies,
        "requests": requests,
        "jobs": jobs_out,
    }


def capture_job(server, job, bodies: list, body_index: dict,
                requests: list, request_index: dict) -> dict:
    """One job's restorable state, in the snapshot's per-job shape.

    Shared by :func:`capture_state` (all jobs, shared dedup tables) and
    the migration export RPC (ISSUE 17 — one job with fresh tables makes
    a self-contained migration record). Lazy array chunks are captured in
    CHUNK form (id ranges + tombstones), so capturing — and migrating — a
    1M-task lazy array is O(chunks), never O(tasks)."""
    from hyperqueue_tpu.server.protocol import rqv_to_wire
    from hyperqueue_tpu.server.task import TaskState

    core = server.core
    done = []
    pending = []
    for info in job.tasks.values():
        if info.status in _TERMINAL:
            done.append([
                info.job_task_id, info.status, info.error,
                info.finished_at, info.started_at, info.submitted_at,
            ])
            continue
        task_id = make_task_id(job.job_id, info.job_task_id)
        task = core.tasks.get(task_id)
        if task is None:
            # jobs-layer entry with no core task: without the core
            # record there is no body/request to rebuild it from, so
            # it cannot ride the snapshot (should not happen outside
            # forget/teardown races — scream if it ever does)
            logger.error(
                "snapshot: non-terminal task %d.%d has no core "
                "record; it will be missing from the snapshot",
                job.job_id, info.job_task_id,
            )
            continue
        body_key = id(task.body)
        body_i = body_index.get(body_key)
        if body_i is None:
            body_i = len(bodies)
            body_index[body_key] = body_i
            bodies.append(task.body)
        rq_i = request_index.get(task.rq_id)
        if rq_i is None:
            rq_i = len(requests)
            request_index[task.rq_id] = rq_i
            requests.append(
                rqv_to_wire(
                    core.rq_map.get_variants(task.rq_id),
                    core.resource_map,
                )
            )
        entry = {
            "id": info.job_task_id,
            "b": body_i,
            "rq": rq_i,
            "priority": task.priority[0],
            "crash_limit": task.crash_limit,
            "deps": [task_id_task(d) for d in task.deps],
            "submitted_at": info.submitted_at,
            "instance": task.instance_id,
            "crashes": task.crash_counter,
            "variant": task.assigned_variant,
            # journal-replay parity: "the last lifecycle event was a
            # start" == the incarnation may still run on a worker that
            # will reconnect and reclaim it. ASSIGNED tasks (compute
            # sent, start not yet reported) have no journaled start, so
            # replay would fence + re-issue them — capture the same.
            "running": (
                task.state is TaskState.RUNNING
                or task_id in server.reattach_pending
            ),
            "stamps": [task.t_ready, task.t_assigned, task.t_started],
        }
        if task.entry is not None:
            entry["entry"] = task.entry
        pending.append(entry)
    jd = {
        "id": job.job_id,
        "name": job.name,
        "submit_dir": job.submit_dir,
        "max_fails": job.max_fails,
        "open": job.is_open,
        "cancel_reason": job.cancel_reason,
        "submitted_at": job.submitted_at,
        "submits": job.submits,
        "done": done,
        "pending": pending,
    }
    # chunked-submit streams (ISSUE 10): applied chunk indexes are the
    # exactly-once fence for client retries; they must survive any
    # restore the journal would have survived
    if job.streams:
        jd["streams"] = {
            uid: {"applied": sorted(s["applied"]),
                  "sealed": bool(s["sealed"])}
            for uid, s in job.streams.items()
        }
    # unmaterialized lazy array chunks: O(chunks + tombstones) — the
    # whole point is that a 1M-task lazy array snapshots (and
    # restores) without expanding to per-task records
    lazy_out = []
    for seg in server.core.lazy.segments_of(job.job_id):
        chunk = seg.chunk
        body_key = id(chunk.body)
        body_i = body_index.get(body_key)
        if body_i is None:
            body_i = len(bodies)
            body_index[body_key] = body_i
            bodies.append(chunk.body)
        rq_i = request_index.get(chunk.rq_id)
        if rq_i is None:
            rq_i = len(requests)
            request_index[chunk.rq_id] = rq_i
            requests.append(
                rqv_to_wire(
                    core.rq_map.get_variants(chunk.rq_id),
                    core.resource_map,
                )
            )
        spec: dict = {
            "b": body_i,
            "rq": rq_i,
            "priority": chunk.priority[0],
            "crash_limit": chunk.crash_limit,
            "submitted_at": chunk.submitted_at,
            "ready_at": chunk.ready_at,
        }
        if chunk.trace:
            spec["trace"] = chunk.trace
        if chunk.id_range is not None and chunk.entries is None:
            spec["id_range"] = [
                chunk.id_range[0] + seg.pos, chunk.id_range[1],
            ]
            dead = [
                chunk.id_at(i) for i in sorted(seg.dead) if i >= seg.pos
            ]
            if dead:
                spec["dead"] = dead
        else:
            remaining = list(seg.remaining_ids())
            spec["ids"] = remaining
            if chunk.entries is not None:
                spec["entries"] = [
                    chunk.entries[chunk.index_of(t)] for t in remaining
                ]
        lazy_out.append(spec)
    if lazy_out:
        jd["lazy"] = lazy_out
    return jd


# --------------------------------------------------------------------------
# write: temp -> fsync -> rotate prev -> atomic rename -> dir fsync
# --------------------------------------------------------------------------
def write_snapshot(journal_path: Path, state: dict) -> Path:
    """Durably publish `state` as the newest snapshot.

    Crash matrix (kill -9 injectable at each named chaos point):
    - mid-snapshot-write: only the temp file is torn; .snap/.snap.prev
      untouched.
    - pre-rename: temp complete but unpublished; old snapshots intact.
    - between the rotations: .snap.prev holds the previously-newest
      snapshot; .snap may be briefly absent — restore falls back to prev.
    - post-rename: the new snapshot is durable; the journal still holds
      everything (GC has not run yet), so restore is merely un-compacted.
    """
    snap = snapshot_path(journal_path)
    prev = prev_snapshot_path(journal_path)
    tmp = Path(str(snap) + ".tmp")
    payload = msgpack.packb(state, use_bin_type=True)
    with open(tmp, "wb") as f:
        f.write(MAGIC)
        f.write(_LEN.pack(len(payload)))
        half = len(payload) // 2
        f.write(payload[:half])
        if chaos.ACTIVE:
            chaos.fire("server.compact", event="mid-snapshot-write")
        f.write(payload[half:])
        f.write(_LEN.pack(zlib.crc32(payload)))
        f.flush()
        os.fsync(f.fileno())
    if chaos.ACTIVE:
        chaos.fire("server.compact", event="pre-rename")
    if snap.exists():
        os.replace(snap, prev)
    os.replace(tmp, snap)
    fsync_dir(snap.parent)
    if chaos.ACTIVE:
        chaos.fire("server.compact", event="post-rename")
    return snap


# --------------------------------------------------------------------------
# load: newest valid snapshot, with fallback
# --------------------------------------------------------------------------
def read_snapshot(path: Path) -> dict:
    """Parse + validate one snapshot file; SnapshotError on any defect."""
    try:
        blob = path.read_bytes()
    except OSError as e:
        raise SnapshotError(f"{path}: {e}") from e
    if len(blob) < len(MAGIC) + _LEN.size or blob[: len(MAGIC)] != MAGIC:
        raise SnapshotError(f"{path}: bad magic")
    (length,) = _LEN.unpack_from(blob, len(MAGIC))
    start = len(MAGIC) + _LEN.size
    if len(blob) < start + length + _LEN.size:
        raise SnapshotError(f"{path}: torn (payload incomplete)")
    payload = blob[start : start + length]
    (crc,) = _LEN.unpack_from(blob, start + length)
    if zlib.crc32(payload) != crc:
        raise SnapshotError(f"{path}: CRC mismatch")
    try:
        state = msgpack.unpackb(payload, raw=False, strict_map_key=False)
    except Exception as e:
        raise SnapshotError(f"{path}: undecodable payload") from e
    if not isinstance(state, dict) or state.get("version") != VERSION:
        raise SnapshotError(
            f"{path}: unsupported snapshot version "
            f"{state.get('version') if isinstance(state, dict) else '?'}"
        )
    for key in ("seq", "n_boots", "jobs", "bodies", "requests"):
        if key not in state:
            raise SnapshotError(f"{path}: missing field {key!r}")
    return state


def iter_snapshot_candidates(journal_path: Path):
    """Yield (state, path) for each readable snapshot, newest first.

    A corrupt/torn newest snapshot logs loudly and falls through to the
    previous one; the caller falls back to full journal replay when the
    iterator is empty."""
    for path in (snapshot_path(journal_path), prev_snapshot_path(journal_path)):
        if not path.exists():
            continue
        try:
            yield read_snapshot(path), path
        except SnapshotError as e:
            logger.error("ignoring unusable snapshot: %s", e)


def snapshot_stats(journal_path: Path) -> dict:
    """Cheap (stat-only) observability fields for `hq journal info` /
    `hq server stats` / the metrics collect hook."""
    out: dict = {"path": None, "bytes": 0, "age_seconds": None}
    snap = snapshot_path(journal_path)
    prev = prev_snapshot_path(journal_path)
    try:
        # stat() directly, no exists() pre-check: a concurrent compaction's
        # rotate window (.snap briefly absent between the two renames)
        # must read as "none right now", not crash the scrape
        st = snap.stat()
        out.update(
            path=str(snap), bytes=st.st_size,
            age_seconds=max(clock.now() - st.st_mtime, 0.0),
        )
    except OSError:
        pass
    try:
        out["prev_bytes"] = prev.stat().st_size
    except OSError:
        out["prev_bytes"] = 0
    return out
