"""Mutual authentication + stream encryption.

Reference: crates/tako/src/internal/transfer/auth.rs:28-226 — challenge-
response HMAC bound to role strings ("hq-server"/"hq-worker"/"hq-client"),
then authenticated stream encryption negotiated per connection, with separate
pre-shared keys for the client plane and the worker plane
(reference common/serverdir.rs:157-188).

Handshake (both directions symmetric):
  1. hello frame (plaintext msgpack): {role, nonce(32B), version, encrypt}
  2. challenge response: HMAC-SHA256(key, peer_nonce || own_role)
  3. on success, directional ChaCha20-Poly1305 keys derived via HKDF over
     both nonces; every subsequent frame body is sealed with a counter nonce.

With key=None both sides must agree encryption is off; frames stay plaintext.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import os
import struct

# backend ladder (transport/aead.py): cryptography's native AEAD, the
# system libcrypto via ctypes, the numpy-vectorized implementation, then
# the pure-python reference — bit-identical wire format across all four,
# forcible via HQ_WIRE_BACKEND
from hyperqueue_tpu.transport.aead import ChaCha20Poly1305

from hyperqueue_tpu import PROTOCOL_VERSION
from hyperqueue_tpu.transport.framing import (
    pack_payload,
    read_frame,
    unpack_payload,
    write_frame,
)

ROLE_SERVER = "hq-server"
ROLE_WORKER = "hq-worker"
ROLE_CLIENT = "hq-client"

_NONCE_CTR = struct.Struct("<Q")


class AuthError(Exception):
    pass


class StreamSeal:
    """Directional ChaCha20-Poly1305 sealing with a monotonically increasing
    counter nonce — replay and reorder within a connection are rejected by
    construction."""

    __slots__ = ("_aead", "_counter", "_prefix")

    def __init__(self, key: bytes, prefix: bytes):
        self._aead = ChaCha20Poly1305(key)
        self._counter = 0
        self._prefix = prefix  # 4 bytes, distinguishes direction

    def _next_nonce(self) -> bytes:
        nonce = self._prefix + _NONCE_CTR.pack(self._counter)
        self._counter += 1
        return nonce

    def seal(self, data) -> bytes:
        return self._aead.encrypt(self._next_nonce(), data, None)

    def open(self, data) -> bytes:
        # memoryview in, so the backend slices ct/tag without copying
        return self._aead.decrypt(self._next_nonce(), memoryview(data), None)


class Connection:
    """A framed, optionally encrypted, msgpack message stream."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        sealer: StreamSeal | None = None,
        opener: StreamSeal | None = None,
    ):
        self.reader = reader
        self.writer = writer
        self._sealer = sealer
        self._opener = opener

    def encode(self, obj) -> bytes:
        """msgpack-encode + seal one frame body WITHOUT writing it —
        the CPU-heavy half of send(), safe to run on a sender-pool
        thread (server/fanout.py) as long as each connection's frames
        are encoded in send order: the seal consumes one counter nonce
        per call, and the peer opens frames in arrival order."""
        data = pack_payload(obj)
        if self._sealer is not None:
            data = self._sealer.seal(data)
        return data

    async def send_bytes(self, data: bytes) -> None:
        """Write one pre-encoded frame body (see encode())."""
        await write_frame(self.writer, data)

    async def send(self, obj) -> None:
        await write_frame(self.writer, self.encode(obj))

    async def recv(self):
        data = await read_frame(self.reader)
        if self._opener is not None:
            data = self._opener.open(data)
        return unpack_payload(data)

    def close(self) -> None:
        try:
            self.writer.close()
        except Exception:
            pass

    async def wait_closed(self) -> None:
        try:
            await self.writer.wait_closed()
        except Exception:
            pass


def _hkdf(key: bytes, salt: bytes, info: bytes) -> bytes:
    prk = hmac.new(salt, key, hashlib.sha256).digest()
    return hmac.new(prk, info + b"\x01", hashlib.sha256).digest()


async def do_authentication(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    my_role: str,
    peer_role: str,
    secret_key: bytes | None,
) -> Connection:
    """Run the symmetric handshake; returns a ready Connection.

    Raises AuthError on role mismatch, bad challenge response, or
    encryption-expectation mismatch (reference auth.rs attack tests
    auth.rs:388-417 cover exactly these cases).
    """
    my_nonce = os.urandom(32)
    encrypt = secret_key is not None
    await write_frame(
        writer,
        pack_payload(
            {
                "role": my_role,
                "nonce": my_nonce,
                "version": PROTOCOL_VERSION,
                "encrypt": encrypt,
            }
        ),
    )
    hello = unpack_payload(await read_frame(reader))
    if hello.get("version") != PROTOCOL_VERSION:
        raise AuthError(f"protocol version mismatch: {hello.get('version')}")
    if hello.get("role") != peer_role:
        raise AuthError(
            f"unexpected peer role {hello.get('role')!r}, wanted {peer_role!r}"
        )
    if bool(hello.get("encrypt")) != encrypt:
        raise AuthError("encryption expectation mismatch")
    peer_nonce = hello["nonce"]
    if not isinstance(peer_nonce, bytes) or len(peer_nonce) != 32:
        raise AuthError("malformed nonce")

    if not encrypt:
        return Connection(reader, writer)

    assert secret_key is not None
    response = hmac.new(
        secret_key, peer_nonce + my_role.encode(), hashlib.sha256
    ).digest()
    await write_frame(writer, pack_payload({"hmac": response}))
    peer_response = unpack_payload(await read_frame(reader))
    expected = hmac.new(
        secret_key, my_nonce + peer_role.encode(), hashlib.sha256
    ).digest()
    if not hmac.compare_digest(peer_response.get("hmac", b""), expected):
        raise AuthError("challenge-response verification failed")

    # directional keys: lexicographic nonce order fixes the direction labels
    salt = min(my_nonce, peer_nonce) + max(my_nonce, peer_nonce)
    key_a = _hkdf(secret_key, salt, b"dir-a")
    key_b = _hkdf(secret_key, salt, b"dir-b")
    if my_nonce < peer_nonce:
        send_key, recv_key = key_a, key_b
        send_prefix, recv_prefix = b"dirA", b"dirB"
    else:
        send_key, recv_key = key_b, key_a
        send_prefix, recv_prefix = b"dirB", b"dirA"
    return Connection(
        reader,
        writer,
        sealer=StreamSeal(send_key, send_prefix),
        opener=StreamSeal(recv_key, recv_prefix),
    )
