"""ChaCha20-Poly1305 AEAD via the system OpenSSL libcrypto (ctypes).

The `openssl` tier of the wire-path backend ladder (transport/aead.py):
this framework's baseline container ships no `cryptography` wheel, but
CPython itself links OpenSSL (the `ssl` module), so libcrypto — with its
assembly ChaCha20-Poly1305 — is always on disk. Binding EVP through
ctypes gets native-speed AEAD (~1-3 ns/wire-byte, ~10 us fixed cost per
call) with zero new dependencies.

Every call uses its own EVP_CIPHER_CTX (thread-safe by construction —
the fan-out sender pool seals from several threads). Prototypes are
declared explicitly: a defaulted int restype would truncate the context
pointer on 64-bit and segfault.

Raises ImportError at import when libcrypto (or the cipher) is missing,
which is exactly how transport/aead.py walks its ladder.
"""

from __future__ import annotations

import ctypes
import ctypes.util


def _load_libcrypto():
    candidates = []
    found = ctypes.util.find_library("crypto")
    if found:
        candidates.append(found)
    candidates += ["libcrypto.so.3", "libcrypto.so.1.1", "libcrypto.so"]
    for name in candidates:
        try:
            return ctypes.CDLL(name)
        except OSError:
            continue
    raise ImportError("libcrypto not loadable")


try:
    _LIB = _load_libcrypto()
    _LIB.EVP_chacha20_poly1305  # noqa: B018 - probe the symbol
except (ImportError, AttributeError) as e:  # pragma: no cover
    raise ImportError(f"OpenSSL ChaCha20-Poly1305 unavailable: {e}") from e

_c_void_p = ctypes.c_void_p
_c_int = ctypes.c_int
_c_char_p = ctypes.c_char_p

_LIB.EVP_CIPHER_CTX_new.restype = _c_void_p
_LIB.EVP_CIPHER_CTX_new.argtypes = ()
_LIB.EVP_CIPHER_CTX_free.restype = None
_LIB.EVP_CIPHER_CTX_free.argtypes = (_c_void_p,)
_LIB.EVP_chacha20_poly1305.restype = _c_void_p
_LIB.EVP_chacha20_poly1305.argtypes = ()
for _name in (
    "EVP_EncryptInit_ex", "EVP_DecryptInit_ex",
):
    fn = getattr(_LIB, _name)
    fn.restype = _c_int
    fn.argtypes = (_c_void_p, _c_void_p, _c_void_p, _c_char_p, _c_char_p)
for _name in ("EVP_EncryptUpdate", "EVP_DecryptUpdate"):
    fn = getattr(_LIB, _name)
    fn.restype = _c_int
    fn.argtypes = (
        _c_void_p, _c_char_p, ctypes.POINTER(_c_int), _c_char_p, _c_int,
    )
for _name in ("EVP_EncryptFinal_ex", "EVP_DecryptFinal_ex"):
    fn = getattr(_LIB, _name)
    fn.restype = _c_int
    fn.argtypes = (_c_void_p, _c_char_p, ctypes.POINTER(_c_int))
_LIB.EVP_CIPHER_CTX_ctrl.restype = _c_int
_LIB.EVP_CIPHER_CTX_ctrl.argtypes = (_c_void_p, _c_int, _c_int, _c_void_p)

_CIPHER = _c_void_p(_LIB.EVP_chacha20_poly1305())
_CTRL_AEAD_SET_IVLEN = 0x9
_CTRL_AEAD_GET_TAG = 0x10
_CTRL_AEAD_SET_TAG = 0x11
_TAG_LEN = 16


class _Ctx:
    __slots__ = ("ptr",)

    def __init__(self):
        self.ptr = _c_void_p(_LIB.EVP_CIPHER_CTX_new())
        if not self.ptr:  # pragma: no cover - allocation failure
            raise MemoryError("EVP_CIPHER_CTX_new failed")

    def __enter__(self):
        return self.ptr

    def __exit__(self, *exc):
        _LIB.EVP_CIPHER_CTX_free(self.ptr)


class ChaCha20Poly1305:
    """Drop-in for cryptography.hazmat...aead.ChaCha20Poly1305."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("ChaCha20Poly1305 key must be 32 bytes")
        self._key = bytes(key)

    def encrypt(self, nonce: bytes, data, associated_data) -> bytes:
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        data = bytes(data)
        aad = bytes(associated_data or b"")
        outl = _c_int(0)
        with _Ctx() as ctx:
            if not (
                _LIB.EVP_EncryptInit_ex(ctx, _CIPHER, None, None, None)
                and _LIB.EVP_CIPHER_CTX_ctrl(
                    ctx, _CTRL_AEAD_SET_IVLEN, 12, None
                )
                and _LIB.EVP_EncryptInit_ex(
                    ctx, None, None, self._key, nonce
                )
            ):  # pragma: no cover - init cannot fail with valid sizes
                raise RuntimeError("EVP encrypt init failed")
            if aad and not _LIB.EVP_EncryptUpdate(
                ctx, None, ctypes.byref(outl), aad, len(aad)
            ):  # pragma: no cover
                raise RuntimeError("EVP aad update failed")
            out = ctypes.create_string_buffer(len(data) + _TAG_LEN)
            if not _LIB.EVP_EncryptUpdate(
                ctx, out, ctypes.byref(outl), data, len(data)
            ):  # pragma: no cover
                raise RuntimeError("EVP encrypt update failed")
            n = outl.value
            fin = ctypes.create_string_buffer(16)
            if not _LIB.EVP_EncryptFinal_ex(
                ctx, fin, ctypes.byref(outl)
            ):  # pragma: no cover
                raise RuntimeError("EVP encrypt final failed")
            n += outl.value  # stream cipher: always 0
            tag = (ctypes.c_char * _TAG_LEN).from_buffer(out, n)
            if not _LIB.EVP_CIPHER_CTX_ctrl(
                ctx, _CTRL_AEAD_GET_TAG, _TAG_LEN, tag
            ):  # pragma: no cover
                raise RuntimeError("EVP get tag failed")
            return out.raw[: n + _TAG_LEN]

    def decrypt(self, nonce: bytes, data, associated_data) -> bytes:
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        data = bytes(data)
        if len(data) < _TAG_LEN:
            raise ValueError("ciphertext too short")
        aad = bytes(associated_data or b"")
        ct, tag = data[:-_TAG_LEN], data[-_TAG_LEN:]
        outl = _c_int(0)
        with _Ctx() as ctx:
            if not (
                _LIB.EVP_DecryptInit_ex(ctx, _CIPHER, None, None, None)
                and _LIB.EVP_CIPHER_CTX_ctrl(
                    ctx, _CTRL_AEAD_SET_IVLEN, 12, None
                )
                and _LIB.EVP_CIPHER_CTX_ctrl(
                    ctx, _CTRL_AEAD_SET_TAG, _TAG_LEN,
                    ctypes.create_string_buffer(tag, _TAG_LEN),
                )
                and _LIB.EVP_DecryptInit_ex(
                    ctx, None, None, self._key, nonce
                )
            ):  # pragma: no cover
                raise RuntimeError("EVP decrypt init failed")
            if aad and not _LIB.EVP_DecryptUpdate(
                ctx, None, ctypes.byref(outl), aad, len(aad)
            ):  # pragma: no cover
                raise RuntimeError("EVP aad update failed")
            out = ctypes.create_string_buffer(len(ct) or 1)
            if not _LIB.EVP_DecryptUpdate(
                ctx, out, ctypes.byref(outl), ct, len(ct)
            ):  # pragma: no cover
                raise RuntimeError("EVP decrypt update failed")
            n = outl.value
            fin = ctypes.create_string_buffer(16)
            if not _LIB.EVP_DecryptFinal_ex(ctx, fin, ctypes.byref(outl)):
                # tag mismatch — same exception contract as the other
                # backends (and cryptography's InvalidTag is a ValueError
                # subclass in spirit; StreamSeal callers catch broadly)
                raise ValueError("MAC check failed")
            return out.raw[:n]
