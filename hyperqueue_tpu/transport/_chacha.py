"""Pure-Python ChaCha20-Poly1305 AEAD (RFC 8439) fallback.

The container this framework runs in does not always ship the
`cryptography` wheel; transport/auth.py gates its import and falls back
to this implementation so servers, workers and clients keep their
authenticated-encryption wire format instead of crashing at import.

Scope: correctness over speed — frames on the control planes are small
msgpack messages, and both sides of a connection negotiate the same
implementation-independent format (RFC 8439 test vectors pinned in
tests/test_tick_cache.py).  Interoperates bit-for-bit with
cryptography.hazmat's ChaCha20Poly1305.
"""

from __future__ import annotations

import hmac
import struct

_MASK32 = 0xFFFFFFFF
_P1305 = (1 << 130) - 5
_CLAMP = 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
_U32X16 = struct.Struct("<16I")


def _quarter(s, a, b, c, d):
    s[a] = (s[a] + s[b]) & _MASK32
    s[d] ^= s[a]
    s[d] = ((s[d] << 16) | (s[d] >> 16)) & _MASK32
    s[c] = (s[c] + s[d]) & _MASK32
    s[b] ^= s[c]
    s[b] = ((s[b] << 12) | (s[b] >> 20)) & _MASK32
    s[a] = (s[a] + s[b]) & _MASK32
    s[d] ^= s[a]
    s[d] = ((s[d] << 8) | (s[d] >> 24)) & _MASK32
    s[c] = (s[c] + s[d]) & _MASK32
    s[b] ^= s[c]
    s[b] = ((s[b] << 7) | (s[b] >> 25)) & _MASK32


def _block(state16: list[int]) -> bytes:
    s = list(state16)
    for _ in range(10):
        _quarter(s, 0, 4, 8, 12)
        _quarter(s, 1, 5, 9, 13)
        _quarter(s, 2, 6, 10, 14)
        _quarter(s, 3, 7, 11, 15)
        _quarter(s, 0, 5, 10, 15)
        _quarter(s, 1, 6, 11, 12)
        _quarter(s, 2, 7, 8, 13)
        _quarter(s, 3, 4, 9, 14)
    return _U32X16.pack(
        *((s[i] + state16[i]) & _MASK32 for i in range(16))
    )


def _chacha20_stream(key: bytes, nonce: bytes, counter: int,
                     length: int) -> bytes:
    base = [
        0x61707865, 0x3320646E, 0x79622D32, 0x6B206574,
        *struct.unpack("<8I", key),
        counter,
        *struct.unpack("<3I", nonce),
    ]
    out = bytearray()
    while len(out) < length:
        out += _block(base)
        base[12] = (base[12] + 1) & _MASK32
    return bytes(out[:length])


def _xor(data: bytes, stream: bytes) -> bytes:
    return (
        int.from_bytes(data, "little") ^ int.from_bytes(stream, "little")
    ).to_bytes(len(data), "little")


def _poly1305(msg: bytes, key: bytes) -> bytes:
    r = int.from_bytes(key[:16], "little") & _CLAMP
    s = int.from_bytes(key[16:32], "little")
    acc = 0
    for i in range(0, len(msg), 16):
        n = int.from_bytes(msg[i:i + 16] + b"\x01", "little")
        acc = ((acc + n) * r) % _P1305
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(data: bytes) -> bytes:
    rem = len(data) % 16
    return b"" if rem == 0 else b"\x00" * (16 - rem)


class ChaCha20Poly1305:
    """Drop-in for cryptography.hazmat...aead.ChaCha20Poly1305."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("ChaCha20Poly1305 key must be 32 bytes")
        self._key = bytes(key)

    def _tag(self, nonce: bytes, ciphertext,
             aad: bytes) -> bytes:
        otk = _chacha20_stream(self._key, nonce, 0, 32)
        mac_data = (
            aad + _pad16(aad)
            # bytes() is a no-op for bytes input and unwraps the
            # zero-copy memoryview the framing layer hands decrypt()
            + bytes(ciphertext) + _pad16(ciphertext)
            + struct.pack("<QQ", len(aad), len(ciphertext))
        )
        return _poly1305(mac_data, otk)

    def encrypt(self, nonce: bytes, data: bytes,
                associated_data: bytes | None) -> bytes:
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        aad = associated_data or b""
        ct = _xor(data, _chacha20_stream(self._key, nonce, 1, len(data)))
        return ct + self._tag(nonce, ct, aad)

    def decrypt(self, nonce: bytes, data: bytes,
                associated_data: bytes | None) -> bytes:
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        if len(data) < 16:
            raise ValueError("ciphertext too short")
        aad = associated_data or b""
        ct, tag = data[:-16], data[-16:]
        if not hmac.compare_digest(self._tag(nonce, ct, aad), tag):
            raise ValueError("MAC check failed")
        return _xor(ct, _chacha20_stream(self._key, nonce, 1, len(ct)))
