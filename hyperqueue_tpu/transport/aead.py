"""Wire-path AEAD backend selection.

One ChaCha20-Poly1305 implementation is chosen at import time for the
whole process (server, worker and client all seal/open with the same
RFC 8439 wire format, so any mix of backends interoperates):

- ``native`` — `cryptography`'s ChaCha20Poly1305 (OpenSSL), ~1 ns/byte.
  Used whenever the wheel is importable.
- ``openssl`` — the same OpenSSL primitive bound directly through
  ctypes (`transport/_chacha_ossl.py`): CPython links libcrypto for the
  `ssl` module, so this tier is native speed with ZERO new dependencies
  — the default on this framework's baseline image.
- ``numpy`` — the vectorized implementation in `transport/_chacha_np.py`
  (~30 ns/byte at batch sizes, see its docstring), for the hypothetical
  box with numpy but no loadable libcrypto.
- ``python`` — the original pure-python fallback in
  `transport/_chacha.py` (~6 us/wire-byte; correctness reference).

``HQ_WIRE_BACKEND`` forces a specific backend (``native``, ``openssl``,
``numpy``, ``python``, or ``auto``) — the compat-path CI lever: a suite run with
``HQ_WIRE_BACKEND=python`` exercises the fallback even where the faster
tiers are installed. The selected name is surfaced in ``hq server info``
(``wire_backend``) and in the bench rows.
"""

from __future__ import annotations

import os

_PREFERENCE = ("native", "openssl", "numpy", "python")


def _load(name: str):
    if name == "native":
        from cryptography.hazmat.primitives.ciphers.aead import (
            ChaCha20Poly1305 as impl,
        )
        return impl
    if name == "openssl":
        from hyperqueue_tpu.transport._chacha_ossl import (
            ChaCha20Poly1305 as impl,
        )
        return impl
    if name == "numpy":
        from hyperqueue_tpu.transport._chacha_np import (
            ChaCha20Poly1305 as impl,
        )
        return impl
    if name == "python":
        from hyperqueue_tpu.transport._chacha import (
            ChaCha20Poly1305 as impl,
        )
        return impl
    raise ValueError(
        f"unknown wire backend {name!r} (expected one of "
        f"{', '.join(_PREFERENCE)}, or auto)"
    )


def available_backends() -> list[str]:
    """Backends importable in this process, best first."""
    out = []
    for name in _PREFERENCE:
        try:
            _load(name)
        except ImportError:
            continue
        out.append(name)
    return out


def select_backend(name: str | None = None):
    """(backend_name, ChaCha20Poly1305 class) for `name`, the
    HQ_WIRE_BACKEND environment override, or auto-preference order.
    A forced backend that cannot import raises — a deployment that pins
    ``native`` must not silently run 1000x slower."""
    forced = name or os.environ.get("HQ_WIRE_BACKEND") or "auto"
    if forced != "auto":
        return forced, _load(forced)
    for candidate in _PREFERENCE:
        try:
            return candidate, _load(candidate)
        except ImportError:
            continue
    raise RuntimeError("no AEAD backend importable")  # pragma: no cover


WIRE_BACKEND, ChaCha20Poly1305 = select_backend()
