"""Numpy-vectorized ChaCha20-Poly1305 AEAD (RFC 8439).

The middle tier of the wire-path backend ladder (transport/aead.py):
containers without the `cryptography` wheel but with numpy (this
framework's baseline — the solver needs it) get a vectorized
implementation instead of the ~6 us/wire-byte pure-python fallback in
`transport/_chacha.py`.

What is (and is not) vectorized, both paths exact:

- **ChaCha20 across the counter axis.** The whole keystream of a message
  (Poly1305 one-time key = block 0, cipher stream = blocks 1..) is one
  batched computation on a ``(16, n)`` uint32 state whose quarter rounds
  run as allocation-free single-row ufunc calls (``out=`` everywhere,
  diagonals addressed by index quadruple instead of np.roll copies),
  chunked so the working state stays cache-resident. The ~1.3k numpy
  calls are a fixed cost per chunk, so the per-byte cost collapses for
  anything beyond a couple of blocks (~10 ns/byte at 64 KiB vs ~4500 for
  the pure-python block function).

- **Poly1305 stays a scalar Horner loop** — deliberately. The candidate
  batched form (Kronecker-packing T coefficients and the powers
  ``r^1..r^T`` into lane-aligned big integers so one CPython big-int
  multiplication yields a T-block dot product) was measured SLOWER than
  the plain loop at every T on CPython 3.11 (38-146 ns/byte vs 23):
  CPython's 30-bit-digit multiplication makes the one big multiply cost
  more than T small ``(acc + c) * r % p`` steps. The loop here is the
  tight-local-variable form of the fallback's, ~23 ns/byte.

Wire format is bit-identical to `cryptography`'s ChaCha20Poly1305 and to
the pure-python fallback (parity pinned in tests/test_wire_backends.py).
"""

from __future__ import annotations

import hmac
import struct

import numpy as np

from hyperqueue_tpu.transport import _chacha as _scalar

_P1305 = (1 << 130) - 5
_CLAMP = 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
_CONST = np.array(
    [0x61707865, 0x3320646E, 0x79622D32, 0x6B206574], dtype=np.uint32
)

# below this many keystream blocks the fixed ~650 us numpy-call cost of
# the batched rounds loses to the scalar block function (~90 us/block,
# measured on the 2-core bench box; see --wire-smoke)
_MIN_VECTOR_BLOCKS = 8

# the 8 quarter-round index quadruples of one double round: 4 columns,
# then 4 diagonals (RFC 8439 section 2.3) — single-row views, so the
# diagonal rounds need no np.roll copies
_QUADS = (
    (0, 4, 8, 12), (1, 5, 9, 13), (2, 6, 10, 14), (3, 7, 11, 15),
    (0, 5, 10, 15), (1, 6, 11, 12), (2, 7, 8, 13), (3, 4, 9, 14),
)
# rotation amounts as uint32 scalars so `out=` never fights promotion
_ROT = {k: (np.uint32(k), np.uint32(32 - k)) for k in (16, 12, 8, 7)}

# keystream chunk (blocks per batched round computation): big enough to
# amortize the ~1.3k-numpy-call fixed cost per chunk, small enough that
# the 16-row uint32 working state (16 * 4 * _CHUNK bytes) stays
# cache-resident
_CHUNK = 4096


def _rot_inplace(v: np.ndarray, k: int, tmp: np.ndarray) -> None:
    left, right = _ROT[k]
    np.left_shift(v, left, out=tmp)
    np.right_shift(v, right, out=v)
    np.bitwise_or(v, tmp, out=v)


def _rounds(x: np.ndarray, tmp: np.ndarray) -> None:
    """20 ChaCha rounds in place on a (16, n) state, allocation-free."""
    rows = [x[i] for i in range(16)]
    for _ in range(10):
        for ai, bi, ci, di in _QUADS:
            a, b, c, d = rows[ai], rows[bi], rows[ci], rows[di]
            np.add(a, b, out=a)
            np.bitwise_xor(d, a, out=d)
            _rot_inplace(d, 16, tmp)
            np.add(c, d, out=c)
            np.bitwise_xor(b, c, out=b)
            _rot_inplace(b, 12, tmp)
            np.add(a, b, out=a)
            np.bitwise_xor(d, a, out=d)
            _rot_inplace(d, 8, tmp)
            np.add(c, d, out=c)
            np.bitwise_xor(b, c, out=b)
            _rot_inplace(b, 7, tmp)


def _keystream(key: bytes, nonce: bytes, counter: int, nblocks: int) -> bytes:
    """`nblocks` ChaCha20 keystream blocks, vectorized across the counter."""
    if nblocks <= 0:
        return b""
    if nblocks < _MIN_VECTOR_BLOCKS:
        return _scalar._chacha20_stream(key, nonce, counter, nblocks * 64)
    key_words = np.frombuffer(key, dtype="<u4")
    nonce_words = np.frombuffer(nonce, dtype="<u4")
    out = np.empty((nblocks, 16), dtype=np.uint32)
    init = np.empty((16, min(nblocks, _CHUNK)), dtype=np.uint32)
    work = np.empty_like(init)
    tmp = np.empty(init.shape[1], dtype=np.uint32)
    for lo in range(0, nblocks, _CHUNK):
        n = min(_CHUNK, nblocks - lo)
        st = init[:, :n]
        st[0:4] = _CONST[:, None]
        st[4:12] = key_words[:, None]
        st[12] = (
            counter + lo + np.arange(n, dtype=np.uint64)
        ).astype(np.uint32)
        st[13:16] = nonce_words[:, None]
        x = work[:, :n]
        x[:] = st
        _rounds(x, tmp[:n])
        x += st
        # block j is column j: transpose into per-block word order
        out[lo:lo + n] = x.T
    if out.dtype.byteorder not in ("<", "="):  # pragma: no cover
        out = out.astype("<u4")
    return out.tobytes()


def _xor_stream(data, stream: bytes) -> bytes:
    n = len(data)
    if n < 256:
        # big-int XOR beats numpy's buffer setup below a few hundred bytes
        return (
            int.from_bytes(data, "little")
            ^ int.from_bytes(stream[:n], "little")
        ).to_bytes(n, "little")
    a = np.frombuffer(data, dtype=np.uint8)
    b = np.frombuffer(stream, dtype=np.uint8, count=n)
    return (a ^ b).tobytes()


def _poly1305(msg: bytes, key: bytes) -> bytes:
    r = int.from_bytes(key[:16], "little") & _CLAMP
    s = int.from_bytes(key[16:32], "little")
    n = len(msg)
    acc = 0
    frm = int.from_bytes  # local binding: this loop runs per 16 bytes
    pad = 1 << 128
    end = n // 16 * 16
    for i in range(0, end, 16):
        acc = (acc + frm(msg[i:i + 16], "little") + pad) * r % _P1305
    if end < n:
        acc = (acc + frm(msg[end:] + b"\x01", "little")) * r % _P1305
    return ((acc + s) & (pad - 1)).to_bytes(16, "little")


def _pad16(n: int) -> bytes:
    rem = n % 16
    return b"" if rem == 0 else b"\x00" * (16 - rem)


class ChaCha20Poly1305:
    """Drop-in for cryptography.hazmat...aead.ChaCha20Poly1305."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("ChaCha20Poly1305 key must be 32 bytes")
        self._key = bytes(key)

    def _mac(self, otk: bytes, ciphertext, aad: bytes) -> bytes:
        mac_data = b"".join((
            aad, _pad16(len(aad)),
            bytes(ciphertext), _pad16(len(ciphertext)),
            struct.pack("<QQ", len(aad), len(ciphertext)),
        ))
        return _poly1305(mac_data, otk)

    def encrypt(self, nonce: bytes, data, associated_data) -> bytes:
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        aad = associated_data or b""
        n = len(data)
        # one keystream pass: block 0 is the Poly1305 one-time key,
        # blocks 1.. are the cipher stream
        ks = _keystream(self._key, nonce, 0, 1 + (n + 63) // 64)
        ct = _xor_stream(data, ks[64:64 + n])
        return ct + self._mac(ks[:32], ct, aad)

    def decrypt(self, nonce: bytes, data, associated_data) -> bytes:
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        if len(data) < 16:
            raise ValueError("ciphertext too short")
        aad = associated_data or b""
        view = memoryview(data)
        ct, tag = view[:-16], view[-16:]
        ks = _keystream(self._key, nonce, 0, 1 + (len(ct) + 63) // 64)
        if not hmac.compare_digest(self._mac(ks[:32], ct, aad), bytes(tag)):
            raise ValueError("MAC check failed")
        return _xor_stream(ct, ks[64:64 + len(ct)])
