"""Length-delimited frames with msgpack payloads.

Reference: crates/tako/src/internal/transfer/transport.rs:4-8 — little-endian
length-prefixed frames, max 128 MiB (lib.rs:31), bincode payloads. We use
msgpack (self-describing, language-neutral) over a u32-LE length prefix.
"""

from __future__ import annotations

import asyncio
import struct

import msgpack

MAX_FRAME_SIZE = 128 * 1024 * 1024
_LEN = struct.Struct("<I")


class FrameError(Exception):
    pass


def pack_payload(obj) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def unpack_payload(data: bytes):
    return msgpack.unpackb(data, raw=False, strict_map_key=False)


async def write_frame(writer: asyncio.StreamWriter, data: bytes) -> None:
    if len(data) > MAX_FRAME_SIZE:
        raise FrameError(f"frame too large: {len(data)}")
    writer.write(_LEN.pack(len(data)) + data)
    await writer.drain()


async def read_frame(reader: asyncio.StreamReader) -> bytes:
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME_SIZE:
        raise FrameError(f"frame too large: {length}")
    return await reader.readexactly(length)
