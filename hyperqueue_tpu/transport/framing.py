"""Length-delimited frames with msgpack payloads.

Reference: crates/tako/src/internal/transfer/transport.rs:4-8 — little-endian
length-prefixed frames, max 128 MiB (lib.rs:31), bincode payloads. We use
msgpack (self-describing, language-neutral) over a u32-LE length prefix.
"""

from __future__ import annotations

import asyncio
import struct

import msgpack

MAX_FRAME_SIZE = 128 * 1024 * 1024
_LEN = struct.Struct("<I")


class FrameError(Exception):
    pass


def pack_payload(obj) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def unpack_payload(data):
    # accepts bytes OR a memoryview (the decrypted-in-place zero-copy
    # path hands views through here)
    return msgpack.unpackb(data, raw=False, strict_map_key=False)


async def write_frame(writer: asyncio.StreamWriter, data: bytes) -> None:
    if len(data) > MAX_FRAME_SIZE:
        raise FrameError(f"frame too large: {len(data)}")
    # two buffered writes instead of one header+body concatenation: the
    # transport coalesces them, and a large sealed frame is not copied a
    # second time just to prepend 4 bytes
    writer.write(_LEN.pack(len(data)))
    writer.write(data)
    await writer.drain()


async def read_frame(reader: asyncio.StreamReader) -> bytes:
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME_SIZE:
        raise FrameError(f"frame too large: {length}")
    return await reader.readexactly(length)


# ---------------------------------------------------------------------
# Trace-context header (ISSUE 8): control-plane messages carry the trace
# id and the sender's span id under one well-known key, so every hop of a
# task's causal trace names its parent.  Kept at the framing layer because
# it is part of the wire contract (client submit, compute downlink, and
# task-state uplinks all stamp it), not any one plane's schema.
# ---------------------------------------------------------------------

TRACE_KEY = "trace"


def attach_trace(msg: dict, trace_id: str, parent: str | None = None,
                 **stamps) -> dict:
    """Stamp a trace-context header onto a message payload (in place)."""
    ctx: dict = {"id": trace_id}
    if parent is not None:
        ctx["parent"] = parent
    ctx.update(stamps)
    msg[TRACE_KEY] = ctx
    return msg


def attach_trace_wire(msg: dict, trace_id: str,
                      parent: str | None) -> dict:
    """Compact per-task form for high-volume planes (compute downlink):
    a two-element array instead of a keyed dict. On deployments stuck on
    the pure-python ChaCha fallback every wire byte is ~6 us of
    encryption, and this header rides EVERY dispatched task."""
    msg[TRACE_KEY] = [trace_id, parent]
    return msg


def read_trace(msg: dict) -> dict | None:
    """The message's trace-context header (either form) as a dict, or
    None when absent/malformed."""
    ctx = msg.get(TRACE_KEY)
    if isinstance(ctx, dict):
        return ctx
    if isinstance(ctx, (list, tuple)) and ctx:
        return {"id": ctx[0], "parent": ctx[1] if len(ctx) > 1 else None}
    return None
