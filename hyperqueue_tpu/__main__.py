from hyperqueue_tpu.client.cli import main

main()
