"""Span tracing around hot runtime phases.

Reference: crates/tako/src/internal/common/trace.rs:1-33 — `trace_time!`
wraps a block in a ScopedTimer that emits start/end tracing events; the
scheduler wraps its whole tick in one (scheduler/main.rs:49). Python
tracing emits are comparatively expensive, so this tracer keeps rolling
per-span statistics (count/total/max/last) plus a small ring of recent
spans in-process, logs each span at DEBUG like the reference's events, and
surfaces the aggregate through `hq server debug-dump` — enough to see
which tick phase (gangs, solve, mapping, prefill) is hot without attaching
a profiler.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field

from hyperqueue_tpu.utils.metrics import REGISTRY

logger = logging.getLogger("hq.trace")

# every span doubles as a histogram series in the metrics plane: the rolling
# SpanStats keep the debug-dump shape, the histogram adds the percentile
# view Prometheus consumers need (utils/metrics.py)
_SPAN_SECONDS = REGISTRY.histogram(
    "hq_span_seconds",
    "duration of traced runtime spans (utils/trace.py TRACER)",
    labels=("span",),
)


@dataclass(slots=True)
class SpanStats:
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0
    last_s: float = 0.0

    def record(self, dt: float) -> None:
        self.count += 1
        self.total_s += dt
        if dt > self.max_s:
            self.max_s = dt
        self.last_s = dt


@dataclass
class Tracer:
    stats: dict[str, SpanStats] = field(default_factory=dict)
    recent: deque = field(default_factory=lambda: deque(maxlen=256))

    def record(self, name: str, dt: float) -> None:
        """Record a measured duration directly (the `span` context manager
        for blocks that a `with` would force to re-indent)."""
        entry = self.stats.get(name)
        if entry is None:
            entry = self.stats[name] = SpanStats()
        entry.record(dt)
        _SPAN_SECONDS.labels(name).observe(dt)
        self.recent.append((name, dt))
        if logger.isEnabledFor(logging.DEBUG):
            logger.debug("span %s: %.3f ms", name, dt * 1000)

    @contextmanager
    def span(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, time.perf_counter() - t0)

    def snapshot(self, recent: int = 16) -> dict:
        """JSON-ready per-span statistics (+ the most recent spans, for
        "what just happened" debugging) for the debug dump."""
        out = {
            name: {
                "count": s.count,
                "total_ms": round(s.total_s * 1000, 3),
                "mean_ms": round(s.total_s / s.count * 1000, 4),
                "max_ms": round(s.max_s * 1000, 3),
                "last_ms": round(s.last_s * 1000, 4),
            }
            for name, s in sorted(self.stats.items())
        }
        if recent:
            out["_recent"] = [
                [name, round(dt * 1000, 4)]
                for name, dt in list(self.recent)[-recent:]
            ]
        return out

    def reset(self) -> None:
        self.stats.clear()
        self.recent.clear()
        _SPAN_SECONDS.reset()


# process-wide tracer (one server or worker per process)
TRACER = Tracer()
span = TRACER.span
