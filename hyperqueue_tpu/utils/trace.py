"""Span tracing around hot runtime phases.

Reference: crates/tako/src/internal/common/trace.rs:1-33 — `trace_time!`
wraps a block in a ScopedTimer that emits start/end tracing events; the
scheduler wraps its whole tick in one (scheduler/main.rs:49). Python
tracing emits are comparatively expensive, so this tracer keeps rolling
per-span statistics (count/total/max/last) plus a small ring of recent
spans in-process, logs each span at DEBUG like the reference's events, and
surfaces the aggregate through `hq server debug-dump` — enough to see
which tick phase (gangs, solve, mapping, prefill) is hot without attaching
a profiler.
"""

from __future__ import annotations

import logging
import secrets
import time
from collections import OrderedDict, deque
from contextlib import contextmanager
from dataclasses import dataclass, field

from hyperqueue_tpu.utils.metrics import REGISTRY

logger = logging.getLogger("hq.trace")

# every span doubles as a histogram series in the metrics plane: the rolling
# SpanStats keep the debug-dump shape, the histogram adds the percentile
# view Prometheus consumers need (utils/metrics.py)
_SPAN_SECONDS = REGISTRY.histogram(
    "hq_span_seconds",
    "duration of traced runtime spans (utils/trace.py TRACER)",
    labels=("span",),
)


@dataclass(slots=True)
class SpanStats:
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0
    last_s: float = 0.0

    def record(self, dt: float) -> None:
        self.count += 1
        self.total_s += dt
        if dt > self.max_s:
            self.max_s = dt
        self.last_s = dt


@dataclass
class Tracer:
    stats: dict[str, SpanStats] = field(default_factory=dict)
    recent: deque = field(default_factory=lambda: deque(maxlen=256))

    def record(self, name: str, dt: float) -> None:
        """Record a measured duration directly (the `span` context manager
        for blocks that a `with` would force to re-indent)."""
        entry = self.stats.get(name)
        if entry is None:
            entry = self.stats[name] = SpanStats()
        entry.record(dt)
        _SPAN_SECONDS.labels(name).observe(dt)
        self.recent.append((name, dt))
        if logger.isEnabledFor(logging.DEBUG):
            logger.debug("span %s: %.3f ms", name, dt * 1000)

    @contextmanager
    def span(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, time.perf_counter() - t0)

    def snapshot(self, recent: int = 16) -> dict:
        """JSON-ready per-span statistics (+ the most recent spans, for
        "what just happened" debugging) for the debug dump."""
        out = {
            name: {
                "count": s.count,
                "total_ms": round(s.total_s * 1000, 3),
                "mean_ms": round(s.total_s / s.count * 1000, 4),
                "max_ms": round(s.max_s * 1000, 3),
                "last_ms": round(s.last_s * 1000, 4),
            }
            for name, s in sorted(self.stats.items())
        }
        if recent:
            out["_recent"] = [
                [name, round(dt * 1000, 4)]
                for name, dt in list(self.recent)[-recent:]
            ]
        return out

    def reset(self) -> None:
        self.stats.clear()
        self.recent.clear()
        _SPAN_SECONDS.reset()


# process-wide tracer (one server or worker per process)
TRACER = Tracer()
span = TRACER.span


# ----------------------------------------------------------------------
# Distributed per-task traces (ISSUE 8).
#
# One trace follows a task from client submit through journal commit,
# solve/dispatch, worker spawn and completion uplink.  Identity is carried
# as a trace id (stamped at submit, journaled, preserved across restore
# and reattach) plus a parent span id on the control-plane messages
# (transport/framing.py attach_trace/read_trace).  Spans are assembled
# SERVER-side in this bounded store — workers only stamp wall clocks onto
# the messages they already send, so the hot dispatch path gains a couple
# of dict writes, never an extra message.
# ----------------------------------------------------------------------

# trace ids ride journal events, so a deterministic run (the simulator's
# bit-identical-journal regression) must be able to derive them from a
# seed instead of the OS entropy pool; production keeps secrets.token_hex
_token_source = secrets.token_hex


def set_token_source(source) -> object:
    """Swap the trace-id entropy source (``fn(nbytes) -> hex str``);
    returns the previous source.  None restores ``secrets.token_hex``."""
    global _token_source
    previous = _token_source
    _token_source = source if source is not None else secrets.token_hex
    return previous


def new_trace_id() -> str:
    return _token_source(8)


# span names, in causal order, for a task launched on a real worker; the
# trace-smoke gate asserts a completed trace contains REQUIRED_HOPS
SPAN_ORDER = (
    "client/submit",   # client send -> server receive (client-stamped)
    "server/submit",   # receive -> tasks built + journal commit
    "server/queue",    # ready -> assigned (scheduler backlog)
    "server/dispatch", # assigned -> worker accepted the compute message
    "worker/accept",   # accepted -> launch dispatched
    "worker/spawn",    # launch dispatched -> process spawned
    "worker/run",      # spawned -> exit
    "worker/uplink",   # completion enqueued -> server received it
    "server/commit",   # received -> state applied + journal commit
)
REQUIRED_HOPS = frozenset(SPAN_ORDER) - {"client/submit"}


class TaskTraceStore:
    """Bounded per-task causal traces (flight-recorder pattern:
    O(1) per span, hard memory bound regardless of uptime).

    One record per task: ``{"trace_id", "spans": [...], "done"}``.  Spans
    are closed intervals ``{"name", "t0", "t1", "proc", "instance", "id",
    "parent"}`` deduplicated on (name, instance) — a reattach or a journal
    replay re-reporting a hop must not double it (the single-timeline
    contract from PR 3).  ``capacity=0`` disables the store entirely.

    Records may also carry fleet ``notes`` (ISSUE 15): point annotations
    stamped by cross-shard machinery — a worker lend (home/host shard), a
    failover promotion (lease epoch) — deduplicated on their identity
    keys so a journal replay or reattach re-reporting one keeps a single
    annotation. They ride snapshots and restores with the spans.
    """

    #: keys that identify an annotation for dedup (everything except the
    #: wall stamp, which legitimately differs between live and replay)
    _NOTE_IDENTITY = ("kind", "instance", "worker", "home_shard",
                      "host_shard", "shard", "lease_epoch")

    def __init__(self, capacity: int = 16384):
        self.capacity = max(int(capacity), 0)
        self.enabled = self.capacity > 0
        self._traces: OrderedDict[int, dict] = OrderedDict()
        # closed task ids in close() order: the O(1) eviction feed (a
        # full-store scan per insert would make a 1M-task submit O(n*cap)
        # on the reactor loop); entries may be stale (already evicted or
        # re-seeded) and are validated when popped
        self._closed: deque = deque()
        self.evictions = 0
        self._span_counter = 0

    def __len__(self) -> int:
        return len(self._traces)

    def new_span_id(self) -> str:
        self._span_counter += 1
        return f"s{self._span_counter:x}"

    def begin(self, task_id: int, trace_id: str) -> dict | None:
        if not self.enabled:
            return None
        rec = self._traces.get(task_id)
        if rec is None:
            rec = {"trace_id": trace_id, "spans": [], "done": False}
            self._traces[task_id] = rec
            self._evict()
        return rec

    def seed(self, task_id: int, rec: dict) -> None:
        """Adopt a restored record (journal replay / snapshot restore)."""
        if not self.enabled or not isinstance(rec, dict):
            return
        done = bool(rec.get("done"))
        adopted = {
            "trace_id": rec.get("trace_id") or new_trace_id(),
            "spans": list(rec.get("spans") or ()),
            "done": done,
        }
        if rec.get("notes"):
            adopted["notes"] = [dict(n) for n in rec["notes"]]
        self._traces[task_id] = adopted
        self._traces.move_to_end(task_id)
        if done:
            self._closed.append(task_id)
        self._evict()

    def span(
        self,
        task_id: int,
        name: str,
        t0: float,
        t1: float,
        proc: str,
        instance: int = 0,
        parent: str | None = None,
    ) -> str | None:
        """Record one closed span; returns its id (None when disabled,
        deduplicated, or the stamps are unusable)."""
        if not self.enabled or not t0 or not t1:
            return None
        rec = self._traces.get(task_id)
        if rec is None:
            rec = self.begin(task_id, new_trace_id())
        for existing in rec["spans"]:
            if existing["name"] == name and existing["instance"] == instance:
                return existing["id"]  # reattach/replay duplicate
        span_id = self.new_span_id()
        rec["spans"].append({
            "name": name,
            "t0": t0,
            "t1": max(t1, t0),  # cross-process clock skew must not make a
            "proc": proc,       # span negative
            "instance": instance,
            "id": span_id,
            "parent": parent,
        })
        return span_id

    def annotate(self, task_id: int, note: dict) -> None:
        """Attach one fleet annotation ({"kind", ...}) to a task's trace.
        Idempotent on the note's identity keys — restore replay and
        reattach re-report the same lend/failover fact."""
        if not self.enabled:
            return
        rec = self._traces.get(task_id)
        if rec is None:
            return
        notes = rec.setdefault("notes", [])
        identity = tuple(note.get(k) for k in self._NOTE_IDENTITY)
        for existing in notes:
            if tuple(
                existing.get(k) for k in self._NOTE_IDENTITY
            ) == identity:
                return
        notes.append(dict(note))

    def annotate_open(self, note: dict) -> int:
        """Annotate every trace still open (not done) — the failover
        promotion stamp: each task that lived through the shard death
        carries the epoch it survived. Returns how many were stamped."""
        stamped = 0
        for task_id, rec in self._traces.items():
            if not rec["done"]:
                self.annotate(task_id, note)
                stamped += 1
        return stamped

    def get(self, task_id: int) -> dict | None:
        return self._traces.get(task_id)

    def trace_id(self, task_id: int) -> str | None:
        rec = self._traces.get(task_id)
        return rec["trace_id"] if rec is not None else None

    def last_span_id(self, task_id: int) -> str | None:
        rec = self._traces.get(task_id)
        if rec is None or not rec["spans"]:
            return None
        return rec["spans"][-1]["id"]

    def wire_ctx(self, task_id: int) -> tuple[str, str | None] | None:
        """(trace_id, last_span_id) in one lookup — the per-task dispatch
        hot path stamps this onto every compute message."""
        rec = self._traces.get(task_id)
        if rec is None:
            return None
        spans = rec["spans"]
        return rec["trace_id"], (spans[-1]["id"] if spans else None)

    def close(self, task_id: int) -> None:
        rec = self._traces.get(task_id)
        if rec is not None and not rec["done"]:
            rec["done"] = True
            self._closed.append(task_id)

    def snapshot_live(self, task_ids) -> dict:
        """{task_id: record} for the given (live) tasks — the piece of
        trace state a journal snapshot must carry so a snapshot-seeded
        restore keeps traces unbroken (the superseded journal prefix that
        held the submit/start events is GC'd).

        Records are COPIED (span dicts are append-only, so copying the
        list suffices): the snapshot payload is serialized on an executor
        thread while the reactor keeps appending spans, and every other
        capture_state field is freshly built for the same reason."""
        out = {}
        for tid in task_ids:
            rec = self._traces.get(tid)
            if rec is not None:
                copied = {
                    "trace_id": rec["trace_id"],
                    "spans": list(rec["spans"]),
                    "done": rec["done"],
                }
                if rec.get("notes"):
                    copied["notes"] = [dict(n) for n in rec["notes"]]
                out[tid] = copied
        return out

    def stats(self) -> dict:
        return {
            "capacity": self.capacity,
            "tasks": len(self._traces),
            "evictions": self.evictions,
            "spans": sum(len(r["spans"]) for r in self._traces.values()),
        }

    def _evict(self) -> None:
        while len(self._traces) > self.capacity:
            # prefer evicting closed traces (oldest-closed first, from the
            # O(1) feed); fall back to the oldest live one so the bound is
            # hard either way
            victim = None
            while self._closed:
                tid = self._closed.popleft()
                rec = self._traces.get(tid)
                if rec is not None and rec["done"]:
                    victim = tid
                    break
            if victim is None:
                victim = next(iter(self._traces))
            del self._traces[victim]
            self.evictions += 1


# ----------------------------------------------------------------------
# Reactor loop-lag tracking (ISSUE 8c): per-plane histograms of how long
# each work class held the server's event loop, plus the loop's own
# sleep-overshoot.  The rolling SpanStats mirror the TRACER shape for
# `hq server stats`; the histogram feeds Prometheus.  The stall watchdog
# (server/bootstrap.py) compares each observation against --stall-budget.
# ----------------------------------------------------------------------

LAG_PLANES = (
    "rpc", "journal", "solve", "fanout", "completion", "ingest", "loop",
)

_REACTOR_LAG_SECONDS = REGISTRY.histogram(
    "hq_reactor_lag_seconds",
    "per-plane server latency: loop occupancy for in-loop work classes "
    "(rpc/solve/completion/ingest) and the loop's own sleep-overshoot "
    "(loop); for the off-loop planes (journal/fanout, ISSUE 12) the "
    "observation is HANDOFF latency — reactor enqueue to durable commit "
    "/ frame on the wire",
    labels=("plane",),
)


class LagTracker:
    """Rolling per-plane loop-occupancy statistics + the shared
    `hq_reactor_lag_seconds` histogram."""

    def __init__(self):
        self.stats: dict[str, SpanStats] = {}

    def observe(self, plane: str, dt: float) -> None:
        entry = self.stats.get(plane)
        if entry is None:
            entry = self.stats[plane] = SpanStats()
        entry.record(dt)
        _REACTOR_LAG_SECONDS.labels(plane).observe(dt)

    def snapshot(self) -> dict:
        return {
            plane: {
                "count": s.count,
                "total_ms": round(s.total_s * 1000, 3),
                "mean_ms": round(s.total_s / s.count * 1000, 4),
                "max_ms": round(s.max_s * 1000, 3),
                "last_ms": round(s.last_s * 1000, 4),
            }
            for plane, s in sorted(self.stats.items())
        }

    def reset(self) -> None:
        self.stats.clear()
        _REACTOR_LAG_SECONDS.reset()
