"""Continuous profiling plane (ISSUE 19): a dependency-free sampling
profiler with per-plane CPU attribution.

A background daemon thread walks ``sys._current_frames()`` at a low,
configurable rate (``--profile-hz``, default ~19 Hz — deliberately prime
so the sampling beat cannot lock onto the scheduler's 10 ms cadence) and
folds every thread's stack into a bounded trie.  Threads self-register a
**plane label** at creation (``register_plane``) — reactor loop, journal
commit thread, fan-out senders, ingest thread, solve/watchdog thread,
worker runtime — so samples aggregate into per-plane CPU-share gauges
(``hq_profile_*``) next to the existing per-plane lag histograms.  Pool
threads that spawn lazily (ThreadPoolExecutor) are labelled by
thread-name prefix instead (``register_plane_prefix``).

Attribution is honest about blocking: ``sys._current_frames`` returns a
frame for every thread, parked or not, so a sample whose leaf frame is a
known wait site (``threading.py:wait``, ``selectors.py:select``,
``queue.py:get``, …) counts as *idle* — a plane's CPU share is its
active samples over the sampling window, not its thread count.

The profiler keeps a bounded ring of recent raw samples so the PR 8
stall detector can attach the stack burst from the exact window in which
the budget was blown (profile-on-stall), and renders flamegraph-
compatible folded stacks (``plane;frame;frame… count``) for
``hq server profile`` / ``hq fleet profile``.

Simulator contract (utils/clock.py): the sampler uses the REAL
``time.perf_counter``/``time.time`` only and refuses to start while a
simulated clock provider is installed — profiling is wall-clock
telemetry and must never perturb (or read) virtual time, so determinism
digests are bit-identical with profiling requested on or off.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque

from hyperqueue_tpu.utils import clock
from hyperqueue_tpu.utils.metrics import REGISTRY

DEFAULT_HZ = 19.0
MAX_STACK_DEPTH = 48
TRUNCATED = "(truncated)"

# --- hq_profile_* instruments (docs/observability.md catalog) -----------
_PLANE_SHARE = REGISTRY.gauge(
    "hq_profile_plane_cpu_share",
    "CPU cores used by each plane over the sampling window "
    "(active samples / sampling passes; >1 on multi-threaded planes)",
    labels=("plane",), max_series=32,
)
_SAMPLES = REGISTRY.counter(
    "hq_profile_samples_total",
    "thread stack samples taken by the sampling profiler",
)
_THREADS = REGISTRY.gauge(
    "hq_profile_threads", "threads seen by the last sampling pass"
)
_TRIE_NODES = REGISTRY.gauge(
    "hq_profile_trie_nodes", "nodes held by the bounded folded-stack trie"
)
_TRIE_DROPPED = REGISTRY.counter(
    "hq_profile_trie_dropped_total",
    "stack frames folded into the (truncated) sink because the trie hit "
    "its node bound",
)


# --- plane registry -----------------------------------------------------
# thread ident -> plane label, written by the thread itself at creation;
# pool threads that spawn lazily match by name prefix instead
_plane_lock = threading.Lock()
_planes: dict[int, str] = {}
_prefixes: list[tuple[str, str]] = [
    ("hq-fanout", "fanout"),
    ("hq-journal", "journal"),
    ("hq-ingest", "ingest"),
    ("hq-solve", "solve"),
    ("hq-device", "solve"),
    ("hq-runner", "runner"),
]


def register_plane(label: str, ident: int | None = None) -> None:
    """Label the calling thread (or ``ident``) as one CPU plane. Call at
    thread entry; a restarted thread re-registers and simply overwrites."""
    with _plane_lock:
        _planes[ident if ident is not None else threading.get_ident()] = label


def unregister_plane(ident: int | None = None) -> None:
    with _plane_lock:
        _planes.pop(
            ident if ident is not None else threading.get_ident(), None
        )


def register_plane_prefix(prefix: str, label: str) -> None:
    """Name-prefix fallback for lazily-spawned pool threads
    (ThreadPoolExecutor names its workers ``<prefix>_N`` at first use,
    long after the pool object existed to register anything)."""
    with _plane_lock:
        for i, (p, _) in enumerate(_prefixes):
            if p == prefix:
                _prefixes[i] = (prefix, label)
                return
        _prefixes.append((prefix, label))


def plane_of(ident: int, name: str) -> str:
    with _plane_lock:
        label = _planes.get(ident)
        if label is not None:
            return label
        for prefix, plane in _prefixes:
            if name.startswith(prefix):
                return plane
    return "other"


def registered_planes() -> dict[int, str]:
    with _plane_lock:
        return dict(_planes)


# --- idle classification ------------------------------------------------
# leaf (file basename, function) pairs that mean "parked, not on-CPU":
# sys._current_frames returns blocked threads too, and a profiler that
# counted a selector sleep as reactor CPU would report 100% everywhere
_WAIT_LEAVES = frozenset({
    ("threading.py", "wait"),
    ("threading.py", "_wait_for_tstate_lock"),
    ("selectors.py", "select"),
    ("queue.py", "get"),
    ("socket.py", "accept"),
    ("socket.py", "recv_into"),
    ("ssl.py", "read"),
    ("subprocess.py", "_try_wait"),
    ("connection.py", "poll"),
    ("popen_fork.py", "poll"),
    ("selector_events.py", "sock_recv"),
})


def is_wait_leaf(filename: str, funcname: str) -> bool:
    return (os.path.basename(filename), funcname) in _WAIT_LEAVES


# --- bounded folded trie ------------------------------------------------
class FoldedTrie:
    """Per-plane stack counts as a bounded trie.

    Nodes are ``{frame_label: [count, children_dict]}``. Once the node
    budget is spent, unseen frames fold into a shared ``(truncated)``
    child per level instead of allocating — long-tail stacks degrade to a
    coarser prefix, memory stays O(max_nodes) forever."""

    def __init__(self, max_nodes: int = 20_000):
        self.max_nodes = max(int(max_nodes), 64)
        self.root: dict = {}
        self.nodes = 0
        self.dropped = 0
        self._lock = threading.Lock()

    def fold(self, plane: str, frames: tuple[str, ...], n: int = 1) -> None:
        """Count one stack (root-first frame labels) under ``plane``."""
        with self._lock:
            children = self.root
            for label in (plane, *frames):
                node = children.get(label)
                if node is None:
                    if self.nodes >= self.max_nodes:
                        self.dropped += 1
                        label = TRUNCATED
                        node = children.get(label)
                        if node is None:
                            # the sink node itself is pre-budgeted: there
                            # is always room for one per level
                            node = children[label] = [0, {}]
                            self.nodes += 1
                        node[0] += n
                        return
                    node = children[label] = [0, {}]
                    self.nodes += 1
                children = node[1]
            node[0] += n

    def counts(self) -> dict[str, int]:
        """``"plane;frame;frame" -> count`` for every counted stack."""
        out: dict[str, int] = {}
        with self._lock:
            stack = [("", self.root)]
            while stack:
                path, children = stack.pop()
                for label, (count, kids) in children.items():
                    key = f"{path};{label}" if path else label
                    if count:
                        out[key] = out.get(key, 0) + count
                    if kids:
                        stack.append((key, kids))
        return out

    def clear(self) -> None:
        with self._lock:
            self.root = {}
            self.nodes = 0
            self.dropped = 0


def render_folded(counts: dict[str, int]) -> str:
    """Flamegraph-compatible folded text: one ``stack count`` per line,
    sorted for stable goldens."""
    return "".join(
        f"{stack} {count}\n" for stack, count in sorted(counts.items())
    )


def diff_counts(after: dict[str, int],
                before: dict[str, int]) -> dict[str, int]:
    """Window view between two cumulative ``counts()`` snapshots."""
    out = {}
    for stack, count in after.items():
        d = count - before.get(stack, 0)
        if d > 0:
            out[stack] = d
    return out


# --- the sampler --------------------------------------------------------
class SamplingProfiler:
    """Background ``sys._current_frames()`` sampler.

    ``publish=True`` (the process singleton) feeds the ``hq_profile_*``
    gauges through a registry collect hook; throwaway instances (tests,
    the ``hq server profile`` burst path on a ``--profile-hz 0`` server)
    keep the registry untouched."""

    def __init__(self, hz: float = DEFAULT_HZ, max_nodes: int = 20_000,
                 ring_capacity: int = 4096, publish: bool = False):
        self.hz = float(hz)
        self.trie = FoldedTrie(max_nodes)
        self.publish = publish
        self.passes = 0
        self.samples = 0
        # rolling ~5 s of per-pass {plane: [samples, active]} for the
        # "current" CPU-share gauges; cumulative totals live in the trie
        self._window: deque = deque(
            maxlen=max(16, min(int(self.hz * 5) or 16, 512))
        )
        # recent raw samples (wall_time, plane, folded_stack, active) —
        # the profile-on-stall burst source
        self.ring: deque = deque(maxlen=ring_capacity)
        self._label_cache: dict = {}
        self._threads_seen = 0
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._hook = None

    # --- lifecycle ------------------------------------------------------
    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> bool:
        """Start sampling; refuses (returns False) under a simulated
        clock — the profiler is real-wall-clock telemetry and must stay
        inert inside the deterministic simulator."""
        if self.hz <= 0 or clock.is_simulated():
            return False
        if self.running:
            return True
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="hq-profiler", daemon=True
        )
        self._thread.start()
        if self.publish and self._hook is None:
            self._hook = self._publish
            REGISTRY.add_collect_hook(self._hook)
        return True

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)
        if self._hook is not None:
            REGISTRY.remove_collect_hook(self._hook)
            self._hook = None

    def reset(self) -> None:
        """Clear every aggregate (the `hq server reset-metrics`
        convention: a steady-state window must not inherit startup CPU)."""
        self.trie.clear()
        self._window.clear()
        self.ring.clear()
        self.passes = 0
        self.samples = 0

    # --- sampling loop --------------------------------------------------
    def _loop(self) -> None:
        interval = 1.0 / self.hz
        own = threading.get_ident()
        next_at = time.perf_counter() + interval
        while not self._stop.is_set():
            delay = next_at - time.perf_counter()
            if delay > 0:
                # Event.wait, not time.sleep: stop() interrupts mid-nap
                if self._stop.wait(delay):
                    break
            next_at = max(next_at + interval, time.perf_counter())
            try:
                self.sample_once(skip={own})
            except Exception:  # noqa: BLE001 - sampling must never kill
                pass           # the process it observes

    def sample_once(self, skip: set[int] | None = None) -> int:
        """One sampling pass over every live thread; returns samples
        taken. Public so tests can drive deterministic passes."""
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        now = time.time()
        pass_stats: dict[str, list] = {}
        taken = 0
        for ident, frame in frames.items():
            if skip and ident in skip:
                continue
            labels, leaf_file, leaf_func = self._walk(frame)
            plane = plane_of(ident, names.get(ident, ""))
            active = not is_wait_leaf(leaf_file, leaf_func)
            self.trie.fold(plane, labels)
            stat = pass_stats.setdefault(plane, [0, 0])
            stat[0] += 1
            stat[1] += int(active)
            self.ring.append((now, plane, ";".join(labels), active))
            taken += 1
        self._window.append(pass_stats)
        self._threads_seen = taken
        self.passes += 1
        self.samples += taken
        if self.publish:
            _SAMPLES.labels().inc(taken)
        return taken

    def _walk(self, frame) -> tuple[tuple[str, ...], str, str]:
        """Root-first frame labels plus the leaf (file, func) pair."""
        cache = self._label_cache
        rev = []
        leaf_code = frame.f_code
        depth = 0
        while frame is not None and depth < MAX_STACK_DEPTH:
            code = frame.f_code
            label = cache.get(code)
            if label is None:
                base = os.path.basename(code.co_filename)
                if base.endswith(".py"):
                    base = base[:-3]
                label = cache[code] = f"{base}.{code.co_name}"
            rev.append(label)
            frame = frame.f_back
            depth += 1
        rev.reverse()
        return tuple(rev), leaf_code.co_filename, leaf_code.co_name

    # --- views ----------------------------------------------------------
    def plane_shares(self) -> dict[str, dict]:
        """Per-plane sample counts + CPU share over the rolling window."""
        window = list(self._window)
        if not window:
            return {}
        out: dict[str, dict] = {}
        for pass_stats in window:
            for plane, (n, active) in pass_stats.items():
                agg = out.setdefault(
                    plane, {"samples": 0, "active": 0, "cpu": 0.0}
                )
                agg["samples"] += n
                agg["active"] += active
        passes = len(window)
        for agg in out.values():
            agg["cpu"] = round(agg["active"] / passes, 4)
        return out

    def snapshot(self) -> dict:
        return {
            "enabled": self.running,
            "hz": self.hz,
            "passes": self.passes,
            "samples": self.samples,
            "threads": self._threads_seen,
            "window_passes": len(self._window),
            "planes": self.plane_shares(),
            "trie": {
                "nodes": self.trie.nodes,
                "max_nodes": self.trie.max_nodes,
                "dropped": self.trie.dropped,
            },
        }

    def folded_counts(self) -> dict[str, int]:
        return self.trie.counts()

    def folded(self) -> str:
        return render_folded(self.folded_counts())

    def stall_burst(self, window_s: float, limit: int = 40) -> list[dict]:
        """Aggregated stacks sampled in the trailing ``window_s`` — the
        profile-on-stall attachment: what every plane was executing while
        the budget was being blown."""
        cutoff = time.time() - max(window_s, 0.0)
        agg: dict[tuple[str, str, bool], int] = {}
        for t, plane, stack, active in reversed(self.ring):
            if t < cutoff:
                break
            key = (plane, stack, active)
            agg[key] = agg.get(key, 0) + 1
        rows = [
            {"plane": plane, "stack": stack, "active": active, "count": n}
            for (plane, stack, active), n in agg.items()
        ]
        rows.sort(key=lambda r: (-r["count"], r["plane"], r["stack"]))
        return rows[:limit]

    def counter_track(self, bucket_s: float = 0.5) -> dict[str, list]:
        """Per-plane (wall_time, cores) series bucketed from the sample
        ring — the Perfetto counter track for `hq server trace export`."""
        per_bucket: dict[str, dict[float, int]] = {}
        for t, plane, _stack, active in self.ring:
            if not active:
                continue
            bucket = round(t - (t % bucket_s), 3)
            per_bucket.setdefault(plane, {})
            per_bucket[plane][bucket] = per_bucket[plane].get(bucket, 0) + 1
        expected = max(self.hz * bucket_s, 1e-9)
        return {
            plane: sorted(
                (t, round(n / expected, 4)) for t, n in buckets.items()
            )
            for plane, buckets in per_bucket.items()
        }

    # --- metrics --------------------------------------------------------
    def _publish(self) -> None:
        _PLANE_SHARE.clear()
        for plane, agg in self.plane_shares().items():
            _PLANE_SHARE.labels(plane).set(agg["cpu"])
        _THREADS.set(self._threads_seen)
        _TRIE_NODES.set(self.trie.nodes)
        _TRIE_DROPPED.labels().set_total(self.trie.dropped)


# --- process singleton --------------------------------------------------
# one server or worker per process (like REGISTRY / TRACER); the CLI's
# --profile-hz lands here through start_profiler
PROFILER = SamplingProfiler(publish=True)


def start_profiler(hz: float = DEFAULT_HZ) -> bool:
    """Configure + start the process profiler (idempotent); False when
    profiling is off (hz <= 0) or a simulated clock is installed."""
    if hz <= 0:
        return False
    if PROFILER.running:
        return True
    PROFILER.hz = float(hz)
    PROFILER._window = deque(
        maxlen=max(16, min(int(hz * 5) or 16, 512))
    )
    return PROFILER.start()


def stop_profiler() -> None:
    PROFILER.stop()
