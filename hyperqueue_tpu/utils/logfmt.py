"""Structured logging: `--log-format json` on server and worker.

A JSON log line carries the same correlation keys the metrics plane and the
flight recorder use — ``tick``, ``job``, ``task``, ``worker``,
``instance``, ``reason`` — so one `jq` pass can join a log stream with
DecisionRecords and Prometheus series.  Call sites attach them through the
stdlib ``extra=`` mechanism::

    logger.warning("worker %d heartbeat timeout", wid, extra={"worker": wid})

Plain format stays the historical human-readable default.
"""

from __future__ import annotations

import json
import logging
import os

# correlation keys promoted from LogRecord attributes into the JSON line
CONTEXT_FIELDS = ("tick", "job", "task", "worker", "instance", "reason")

LOG_FORMATS = ("plain", "json")


class JsonLogFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        for key in CONTEXT_FIELDS:
            value = record.__dict__.get(key)
            if value is not None:
                out[key] = value
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def setup_logging(log_format: str | None = None, level: str | None = None):
    """Configure root logging for a server/worker process.

    `log_format`: "plain" | "json"; None falls back to $HQ_LOG_FORMAT then
    plain. `level` falls back to $HQ_LOG then INFO.
    """
    if log_format is None:
        log_format = os.environ.get("HQ_LOG_FORMAT", "plain")
    if log_format not in LOG_FORMATS:
        raise ValueError(f"unknown log format {log_format!r}")
    handler = logging.StreamHandler()
    if log_format == "json":
        handler.setFormatter(JsonLogFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"
        ))
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel((level or os.environ.get("HQ_LOG", "INFO")).upper())
    return handler
