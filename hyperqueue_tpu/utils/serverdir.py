"""Server directory and access records.

Reference: crates/hyperqueue/src/common/serverdir.rs:18-216 — a per-server
directory (default ~/.hq-tpu-server/NNN) holding access.json with host/ports
and the two pre-shared secret keys (client plane, worker plane), plus an
`hq-current` symlink to the newest instance. `generate-access` style
pre-shared deployment works by copying this file.

Federation (ISSUE 11): a federated deployment nests one classic server dir
per shard under the root (``<root>/shard-0000``, ``shard-0001``, ...), each
with its own instance dirs, journal, and lease file, plus a root-level
``federation.json`` naming the shard count. Job ids partition statically:
shard k of N owns every job id with ``(job_id - 1) % N == k``, so a job id
alone routes a client to its shard and shards allocate without
coordination.
"""

from __future__ import annotations

import json
import os
import secrets
import time
from dataclasses import dataclass
from pathlib import Path
from hyperqueue_tpu.utils import clock

ACCESS_FILE = "access.json"
CURRENT_LINK = "hq-current"
FEDERATION_FILE = "federation.json"
# failover rewrites the access record while workers/clients re-read it
# under --on-server-lost reconnect; a reader that catches the rename
# window (or a torn legacy writer) retries briefly instead of failing
LOAD_ACCESS_RETRY_SECS = 0.5
_LOAD_ACCESS_POLL = 0.02


@dataclass
class AccessRecord:
    server_uid: str
    host: str
    client_port: int
    worker_port: int
    client_key: str | None  # hex; None = auth disabled on that plane
    worker_key: str | None
    version: int = 1
    # server visible under a different hostname to workers than to clients
    # (reference serverdir.rs FullAccessRecord: per-plane host)
    worker_host: str | None = None

    def host_for_workers(self) -> str:
        return self.worker_host or self.host

    def to_json(self, role: str | None = None) -> dict:
        """Full record, or a split single-plane record when role is
        "client"/"worker" (reference `generate-access --client-file/
        --worker-file` splitting)."""
        out: dict = {"version": self.version, "server_uid": self.server_uid}
        if role in (None, "client"):
            out["client"] = {
                "host": self.host, "port": self.client_port,
                "key": self.client_key,
            }
        if role in (None, "worker"):
            out["worker"] = {
                "host": self.host_for_workers(), "port": self.worker_port,
                "key": self.worker_key,
            }
        return out

    @classmethod
    def from_json(cls, data: dict) -> "AccessRecord":
        client = data.get("client")
        worker = data.get("worker")
        if client is None and worker is None:
            raise ValueError("access record has neither client nor worker plane")
        return cls(
            server_uid=data["server_uid"],
            host=(client or worker)["host"],
            client_port=client["port"] if client else 0,
            worker_port=worker["port"] if worker else 0,
            client_key=client.get("key") if client else None,
            worker_key=worker.get("key") if worker else None,
            version=data.get("version", 1),
            worker_host=worker["host"] if worker else None,
        )

    def client_key_bytes(self) -> bytes | None:
        return bytes.fromhex(self.client_key) if self.client_key else None

    def worker_key_bytes(self) -> bytes | None:
        return bytes.fromhex(self.worker_key) if self.worker_key else None


def default_server_dir() -> Path:
    root = os.environ.get("HQ_SERVER_DIR")
    if root:
        return Path(root)
    return Path.home() / ".hq-tpu-server"


# server uids land in the journal (server-uid lineage records), so a
# deterministic simulation must be able to derive them from a seed; the
# default stays the OS entropy pool. Key material also flows through this
# source — a simulation that wants encryption must accept that a seeded
# source makes those keys predictable (the sim runs auth-disabled).
_token_source = secrets.token_hex


def set_token_source(source) -> object:
    """Swap the uid/key entropy source (``fn(nbytes) -> hex str``);
    returns the previous source.  None restores ``secrets.token_hex``."""
    global _token_source
    previous = _token_source
    _token_source = source if source is not None else secrets.token_hex
    return previous


def generate_access(
    host: str,
    client_port: int,
    worker_port: int,
    disable_client_auth: bool = False,
    disable_worker_auth: bool = False,
    worker_host: str | None = None,
) -> AccessRecord:
    return AccessRecord(
        server_uid=_token_source(8),
        host=host,
        client_port=client_port,
        worker_port=worker_port,
        client_key=None if disable_client_auth else _token_source(32),
        worker_key=None if disable_worker_auth else _token_source(32),
        worker_host=worker_host,
    )


def create_instance_dir(server_dir: Path) -> Path:
    """Create server_dir/NNN (next free number) and point hq-current at it."""
    server_dir.mkdir(parents=True, exist_ok=True)
    n = 1
    existing = [
        int(p.name) for p in server_dir.iterdir() if p.name.isdigit()
    ]
    if existing:
        n = max(existing) + 1
    instance = server_dir / f"{n:03d}"
    instance.mkdir()
    link = server_dir / CURRENT_LINK
    tmp = server_dir / f".{CURRENT_LINK}.tmp"
    if tmp.is_symlink() or tmp.exists():
        tmp.unlink()
    tmp.symlink_to(instance.name)
    tmp.replace(link)
    return instance


def store_access(instance_dir: Path, record: AccessRecord) -> None:
    # atomic: the hq-current symlink already points at this instance dir
    # (create_instance_dir flips it first), so reconnecting workers and
    # retrying clients poll this path — they must see nothing or the whole
    # record, never a torn write. The rename must also survive a crash of
    # the PUBLISHER (a promoted successor dying right after failover must
    # not leave the old, dead address on disk): fsync the dir too.
    path = instance_dir / ACCESS_FILE
    tmp = instance_dir / f".{ACCESS_FILE}.tmp"
    with open(tmp, "w") as f:
        json.dump(record.to_json(), f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.chmod(tmp, 0o600)
    tmp.replace(path)
    from hyperqueue_tpu.events.journal import fsync_dir

    fsync_dir(instance_dir)


def _read_access_file(path: Path) -> AccessRecord:
    with open(path) as f:
        return AccessRecord.from_json(json.load(f))


def load_access(
    server_dir: Path, retry_secs: float | None = None
) -> AccessRecord:
    """Load the current instance's access record.

    Tolerates a record mid-rewrite: shard failover publishes a NEW
    instance dir + access record while reconnecting workers and retrying
    clients re-read this path, and a non-atomic writer (an out-of-tree
    tool editing access.json in place) can expose a torn prefix. A parse
    error or a file vanishing between the symlink hop and the open is
    retried for a short window before it propagates.
    """
    window = LOAD_ACCESS_RETRY_SECS if retry_secs is None else retry_secs
    deadline = clock.monotonic() + window
    while True:
        direct = server_dir / ACCESS_FILE
        try:
            if direct.exists():
                return _read_access_file(direct)
            current = server_dir / CURRENT_LINK
            path = current / ACCESS_FILE
            if not path.exists():
                raise FileNotFoundError(
                    f"no running server found in {server_dir} "
                    f"(missing {ACCESS_FILE})"
                )
            return _read_access_file(path)
        except FileNotFoundError:
            # the instance dir exists but its record does not (yet): only
            # a publish-in-progress window is worth riding out — with no
            # hq-current symlink at all, fail fast with the clear message
            if not (server_dir / CURRENT_LINK).is_symlink():
                raise
            if clock.monotonic() >= deadline:
                raise
        except (ValueError, KeyError, TypeError):
            # torn/mid-rewrite record (json decode errors are ValueError);
            # retry briefly, then let the real error out
            if clock.monotonic() >= deadline:
                raise
        time.sleep(_LOAD_ACCESS_POLL)


# ------------------------------------------------------------- federation
def shard_dir_name(shard_id: int) -> str:
    return f"shard-{shard_id:04d}"


def shard_path(root: Path, shard_id: int) -> Path:
    return Path(root) / shard_dir_name(shard_id)


def shard_id_of(server_dir: Path) -> int | None:
    """Shard id encoded in a shard server-dir name, or None."""
    name = Path(server_dir).name
    if name.startswith("shard-") and name[6:].isdigit():
        return int(name[6:])
    return None


def shard_for_job(job_id: int, shard_count: int) -> int:
    """The modulo partition primitive (static; ids are 1-based).

    This is only the PRE-MIGRATION fallback since ISSUE 17: live routing
    must go through ``client/routing.py``'s resolver, which consults the
    ownership map first (committed migrations and online-added shards
    re-home job ids away from this arithmetic)."""
    return (int(job_id) - 1) % max(int(shard_count), 1)


def write_federation(root: Path, shard_count: int) -> dict:
    """Publish (or validate) the root-level federation descriptor and
    create the shard dirs. Idempotent; a conflicting shard count is a
    hard error — the partition is static for the journal lineages'
    lifetime (re-sharding would re-home job ids between journals). The
    check-then-write runs under a flock so N concurrently-booting shards
    with DISAGREEING --shards values cannot both pass validation."""
    import fcntl

    root = Path(root)
    root.mkdir(parents=True, exist_ok=True)
    lock_fd = os.open(root / ".federation.lock", os.O_CREAT | os.O_RDWR,
                      0o600)
    try:
        fcntl.flock(lock_fd, fcntl.LOCK_EX)
        existing = load_federation(root)
        if existing is not None:
            if existing["shard_count"] != shard_count:
                raise ValueError(
                    f"federation at {root} has {existing['shard_count']} "
                    f"shard(s); refusing to restart it with {shard_count} "
                    f"(online growth goes through grow_federation / "
                    f"`hq server start --shard-id {existing['shard_count']}"
                    f" --shards {existing['shard_count'] + 1}`)"
                )
            return existing
        record = {
            "version": 1,
            "shard_count": int(shard_count),
            # the MODULO partition width, frozen forever: online shard
            # adds bump shard_count but never this — pre-existing job
            # ids are baked into the original journal lineages
            "base_shard_count": int(shard_count),
        }
        _publish_federation(root, record)
        for k in range(shard_count):
            shard_path(root, k).mkdir(exist_ok=True)
        return record
    finally:
        os.close(lock_fd)


def _publish_federation(root: Path, record: dict) -> None:
    tmp = root / f".{FEDERATION_FILE}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    tmp.replace(root / FEDERATION_FILE)
    from hyperqueue_tpu.events.journal import fsync_dir

    fsync_dir(root)


def grow_federation(root: Path, shard_count: int) -> dict:
    """Grow an existing federation to `shard_count` shards ONLINE.

    The explicit growth path (ISSUE 17): rewrites the descriptor with the
    larger count (base_shard_count unchanged — the modulo partition stays
    frozen at the boot-time width), creates the new shard dirs, and
    journals a shard-add record per new shard in the ownership log so
    clients and the coordinator learn the new member without any restart
    of the existing shards. Shrinking remains a hard error."""
    import fcntl

    root = Path(root)
    lock_fd = os.open(root / ".federation.lock", os.O_CREAT | os.O_RDWR,
                      0o600)
    try:
        fcntl.flock(lock_fd, fcntl.LOCK_EX)
        existing = load_federation(root)
        if existing is None:
            raise ValueError(
                f"no federation at {root} to grow; boot one with --shards"
            )
        old_count = int(existing["shard_count"])
        if shard_count < old_count:
            raise ValueError(
                f"federation at {root} has {old_count} shard(s); shrinking "
                f"to {shard_count} is not supported — drain instead"
            )
        if shard_count == old_count:
            return existing
        record = dict(existing)
        record["shard_count"] = int(shard_count)
        record["base_shard_count"] = int(existing["base_shard_count"])
        _publish_federation(root, record)
        for k in range(shard_count):
            shard_path(root, k).mkdir(exist_ok=True)
    finally:
        os.close(lock_fd)
    from hyperqueue_tpu.utils.ownership import OwnershipStore

    store = OwnershipStore(root)
    for k in range(old_count, shard_count):
        store.record_shard_add(k, shard_count)
    return record


def load_federation(root: Path) -> dict | None:
    """The federation descriptor at `root`, or None for a classic
    single-server dir."""
    path = Path(root) / FEDERATION_FILE
    if not path.exists():
        return None
    with open(path) as f:
        data = json.load(f)
    if int(data.get("shard_count", 0)) < 1:
        raise ValueError(f"malformed federation descriptor {path}")
    data["shard_count"] = int(data["shard_count"])
    # pre-ISSUE-17 descriptors had no base_shard_count: the federation
    # never grew, so the modulo width IS the shard count
    data["base_shard_count"] = int(
        data.get("base_shard_count", data["shard_count"])
    )
    return data
