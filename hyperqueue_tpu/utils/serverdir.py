"""Server directory and access records.

Reference: crates/hyperqueue/src/common/serverdir.rs:18-216 — a per-server
directory (default ~/.hq-tpu-server/NNN) holding access.json with host/ports
and the two pre-shared secret keys (client plane, worker plane), plus an
`hq-current` symlink to the newest instance. `generate-access` style
pre-shared deployment works by copying this file.
"""

from __future__ import annotations

import json
import os
import secrets
from dataclasses import dataclass
from pathlib import Path

ACCESS_FILE = "access.json"
CURRENT_LINK = "hq-current"


@dataclass
class AccessRecord:
    server_uid: str
    host: str
    client_port: int
    worker_port: int
    client_key: str | None  # hex; None = auth disabled on that plane
    worker_key: str | None
    version: int = 1
    # server visible under a different hostname to workers than to clients
    # (reference serverdir.rs FullAccessRecord: per-plane host)
    worker_host: str | None = None

    def host_for_workers(self) -> str:
        return self.worker_host or self.host

    def to_json(self, role: str | None = None) -> dict:
        """Full record, or a split single-plane record when role is
        "client"/"worker" (reference `generate-access --client-file/
        --worker-file` splitting)."""
        out: dict = {"version": self.version, "server_uid": self.server_uid}
        if role in (None, "client"):
            out["client"] = {
                "host": self.host, "port": self.client_port,
                "key": self.client_key,
            }
        if role in (None, "worker"):
            out["worker"] = {
                "host": self.host_for_workers(), "port": self.worker_port,
                "key": self.worker_key,
            }
        return out

    @classmethod
    def from_json(cls, data: dict) -> "AccessRecord":
        client = data.get("client")
        worker = data.get("worker")
        if client is None and worker is None:
            raise ValueError("access record has neither client nor worker plane")
        return cls(
            server_uid=data["server_uid"],
            host=(client or worker)["host"],
            client_port=client["port"] if client else 0,
            worker_port=worker["port"] if worker else 0,
            client_key=client.get("key") if client else None,
            worker_key=worker.get("key") if worker else None,
            version=data.get("version", 1),
            worker_host=worker["host"] if worker else None,
        )

    def client_key_bytes(self) -> bytes | None:
        return bytes.fromhex(self.client_key) if self.client_key else None

    def worker_key_bytes(self) -> bytes | None:
        return bytes.fromhex(self.worker_key) if self.worker_key else None


def default_server_dir() -> Path:
    root = os.environ.get("HQ_SERVER_DIR")
    if root:
        return Path(root)
    return Path.home() / ".hq-tpu-server"


def generate_access(
    host: str,
    client_port: int,
    worker_port: int,
    disable_client_auth: bool = False,
    disable_worker_auth: bool = False,
    worker_host: str | None = None,
) -> AccessRecord:
    return AccessRecord(
        server_uid=secrets.token_hex(8),
        host=host,
        client_port=client_port,
        worker_port=worker_port,
        client_key=None if disable_client_auth else secrets.token_hex(32),
        worker_key=None if disable_worker_auth else secrets.token_hex(32),
        worker_host=worker_host,
    )


def create_instance_dir(server_dir: Path) -> Path:
    """Create server_dir/NNN (next free number) and point hq-current at it."""
    server_dir.mkdir(parents=True, exist_ok=True)
    n = 1
    existing = [
        int(p.name) for p in server_dir.iterdir() if p.name.isdigit()
    ]
    if existing:
        n = max(existing) + 1
    instance = server_dir / f"{n:03d}"
    instance.mkdir()
    link = server_dir / CURRENT_LINK
    tmp = server_dir / f".{CURRENT_LINK}.tmp"
    if tmp.is_symlink() or tmp.exists():
        tmp.unlink()
    tmp.symlink_to(instance.name)
    tmp.replace(link)
    return instance


def store_access(instance_dir: Path, record: AccessRecord) -> None:
    # atomic: the hq-current symlink already points at this instance dir
    # (create_instance_dir flips it first), so reconnecting workers and
    # retrying clients poll this path — they must see nothing or the whole
    # record, never a torn write
    path = instance_dir / ACCESS_FILE
    tmp = instance_dir / f".{ACCESS_FILE}.tmp"
    with open(tmp, "w") as f:
        json.dump(record.to_json(), f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.chmod(tmp, 0o600)
    tmp.replace(path)


def load_access(server_dir: Path) -> AccessRecord:
    """Load the current instance's access record."""
    direct = server_dir / ACCESS_FILE
    if direct.exists():
        with open(direct) as f:
            return AccessRecord.from_json(json.load(f))
    current = server_dir / CURRENT_LINK
    path = current / ACCESS_FILE
    if not path.exists():
        raise FileNotFoundError(
            f"no running server found in {server_dir} (missing {ACCESS_FILE})"
        )
    with open(path) as f:
        return AccessRecord.from_json(json.load(f))
