"""Server flight recorder: a bounded in-memory ring of scheduling history.

Black-box style: the last N per-tick DecisionRecords (scheduler/decision.py)
plus recent control-plane events (worker connect/lost, job submit/pause,
solver degradation) are kept in fixed-size rings, costing O(1) per tick and
a hard memory bound regardless of uptime.  ``hq server flight-recorder
dump`` exposes the rings; ``hq task explain`` joins them to answer "why is
this task not running and for how long"; ``hq server trace export`` folds
the tick ring into the scheduler row of the Perfetto timeline.

Idle ticks (nothing ready, nothing unplaced, nothing assigned) are dropped
so the ring's N ticks cover N ticks of actual scheduling work, not a quiet
night of heartbeats.
"""

from __future__ import annotations

import time
from collections import deque
from hyperqueue_tpu.utils import clock

DEFAULT_TICKS = 512
DEFAULT_EVENTS = 1024


class FlightRecorder:
    """Ring buffers of DecisionRecords + control-plane events.

    ``capacity_ticks=0`` disables recording entirely (``record_tick`` and
    ``record_event`` become no-ops) for deployments that want the last few
    bytes of tick budget back.
    """

    def __init__(
        self,
        capacity_ticks: int = DEFAULT_TICKS,
        capacity_events: int = DEFAULT_EVENTS,
    ):
        self.capacity_ticks = max(int(capacity_ticks), 0)
        self.enabled = self.capacity_ticks > 0
        self._ticks: deque = deque(maxlen=self.capacity_ticks or 1)
        self._events: deque = deque(maxlen=max(int(capacity_events), 1))
        self.dropped_idle_ticks = 0

    # --- recording ----------------------------------------------------
    def record_tick(self, record: dict) -> None:
        if not self.enabled:
            return
        counts = record.get("counts") or {}
        if not (
            counts.get("assigned")
            or counts.get("prefilled")
            or counts.get("unplaced")
            or counts.get("gang_assigned")
            or counts.get("paused")
        ):
            # idle tick: keep the ring's window on real decisions
            self.dropped_idle_ticks += 1
            return
        self._ticks.append(record)

    def record_event(self, kind: str, payload: dict | None = None) -> None:
        if not self.enabled:
            return
        self._events.append(
            {"time": clock.now(), "event": kind, **(payload or {})}
        )

    # --- queries ------------------------------------------------------
    def ticks(self) -> list[dict]:
        return list(self._ticks)

    def events(self) -> list[dict]:
        return list(self._events)

    def latest(self) -> dict | None:
        return self._ticks[-1] if self._ticks else None

    def reason_for(self, rq_id: int | None, job: int) -> dict | None:
        """Latest unplaced entry for (class, job), annotated with how many
        consecutive recent ticks the pair stayed unplaced (`deferred_ticks`,
        capped by the ring capacity) and the tick id it was last seen on.

        `rq_id=None` matches the job alone (paused/gang entries carry no
        class).
        """

        def match(record) -> dict | None:
            for entry in record.get("unplaced") or ():
                if entry.get("job") != job:
                    continue
                if rq_id is None or entry.get("rq_id") in (rq_id, None):
                    return entry
            return None

        latest_entry = None
        latest_tick = None
        deferred = 0
        for record in reversed(self._ticks):
            entry = match(record)
            if entry is None:
                break
            deferred += 1
            if latest_entry is None:
                latest_entry = entry
                latest_tick = record.get("tick")
        if latest_entry is None:
            return None
        # streak spans the whole (full) ring: the true deferral is >= this
        capped = (
            deferred == len(self._ticks)
            and len(self._ticks) == self.capacity_ticks
        )
        return {
            **latest_entry,
            "tick": latest_tick,
            "deferred_ticks": deferred,
            "deferred_capped": capped,
        }

    def dump(self) -> dict:
        return {
            "capacity_ticks": self.capacity_ticks,
            "capacity_events": self._events.maxlen,
            "dropped_idle_ticks": self.dropped_idle_ticks,
            "ticks": self.ticks(),
            "events": self.events(),
        }
