"""ctypes loader for the native core (libhqcore.so).

Builds lazily via make on first use if the shared library is missing; falls
back silently to the pure-Python implementations when the toolchain is
unavailable (the Python and native structures share their semantics and the
test suite runs both).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from pathlib import Path

logger = logging.getLogger(__name__)

NATIVE_DIR = Path(__file__).resolve().parent.parent / "native"
LIB_PATH = NATIVE_DIR / "libhqcore.so"

_lib = None
_tried = False


def load_native():
    """Returns the ctypes lib or None."""
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("HQ_DISABLE_NATIVE"):
        return None
    try:
        import fcntl

        # concurrent processes (test server + workers) may race to build;
        # serialize via flock. make runs unconditionally — a fresh .so is a
        # no-op, and a STALE .so (built before a symbol was added) would
        # otherwise fail the prototype setup below
        with open(NATIVE_DIR / ".build.lock", "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            subprocess.run(
                ["make", "-C", str(NATIVE_DIR)],
                capture_output=True,
                timeout=120,
                check=True,
            )
    except (OSError, subprocess.CalledProcessError,
            subprocess.TimeoutExpired) as e:
        logger.debug("native build unavailable: %s", e)
        if not LIB_PATH.exists():
            return None
    try:
        lib = ctypes.CDLL(str(LIB_PATH))
        _set_prototypes(lib)
    except (OSError, AttributeError) as e:
        # AttributeError = a stale .so missing a newer symbol (make failed
        # or raced); fall back to the Python implementations
        logger.debug("native load failed: %s", e)
        return None
    _lib = lib
    return _lib


def _set_prototypes(lib) -> None:
    lib.hq_queue_new.restype = ctypes.c_void_p
    lib.hq_queue_free.argtypes = [ctypes.c_void_p]
    lib.hq_queue_add.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_uint64,
    ]
    lib.hq_queue_remove.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.hq_queue_len.argtypes = [ctypes.c_void_p]
    lib.hq_queue_len.restype = ctypes.c_int64
    lib.hq_queue_priority_sizes.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
    ]
    lib.hq_queue_priority_sizes.restype = ctypes.c_int64
    lib.hq_queue_take.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.hq_queue_take.restype = ctypes.c_int64
    lib.hq_queue_all.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64,
    ]
    lib.hq_queue_all.restype = ctypes.c_int64
    lib.hq_map_take.argtypes = [
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.hq_map_take.restype = ctypes.c_int64
    lib.hq_cut_scan.argtypes = [
        ctypes.POINTER(ctypes.c_int64),   # free (W,R)
        ctypes.POINTER(ctypes.c_int64),   # total (W,R) or NULL
        ctypes.POINTER(ctypes.c_int64),   # nt (W)
        ctypes.POINTER(ctypes.c_int32),   # lifetime (W)
        ctypes.POINTER(ctypes.c_int64),   # needs (B,V,R)
        ctypes.POINTER(ctypes.c_int32),   # all_mask (B,V,R) or NULL
        ctypes.POINTER(ctypes.c_int64),   # sizes (B)
        ctypes.POINTER(ctypes.c_int32),   # min_time (B,V)
        ctypes.POINTER(ctypes.c_int32),   # class_m (M,W)
        ctypes.POINTER(ctypes.c_int32),   # order_ids (B,V)
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64,                   # W R B V M
        ctypes.POINTER(ctypes.c_int32),   # counts out (B,V,W)
    ]
    lib.hq_cut_scan.restype = None
    lib.hq_nonzero.argtypes = [
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
    ]
    lib.hq_nonzero.restype = ctypes.c_int64


def native_cut_scan(
    free, nt_free, lifetime, needs, sizes, min_time, class_m, order_ids,
    total=None, all_mask=None,
):
    """Native host solve with the numpy fallback's exact semantics
    (ops/assign.greedy_cut_scan_numpy); returns counts (B,V,W) int32 or
    None when the native lib is unavailable."""
    lib = load_native()
    if lib is None:
        return None
    import numpy as np

    free = np.ascontiguousarray(free, dtype=np.int64)
    nt = np.ascontiguousarray(nt_free, dtype=np.int64)
    life = np.ascontiguousarray(lifetime, dtype=np.int32)
    needs = np.ascontiguousarray(needs, dtype=np.int64)
    sizes = np.ascontiguousarray(sizes, dtype=np.int64)
    mt = np.ascontiguousarray(min_time, dtype=np.int32)
    cm = np.ascontiguousarray(class_m, dtype=np.int32)
    oi = np.ascontiguousarray(order_ids, dtype=np.int32)
    n_w, n_r = free.shape
    n_b, n_v, _ = needs.shape
    counts = np.zeros((n_b, n_v, n_w), dtype=np.int32)

    def ptr(a, ct):
        return a.ctypes.data_as(ctypes.POINTER(ct))

    total_p = None
    amask_p = None
    if all_mask is not None:
        if total is None:
            # the numpy reference reads total[:, all_r] and would raise;
            # silently substituting free would grant ALL requests on
            # partially-busy workers
            raise ValueError("all_mask requires total")
        total = np.ascontiguousarray(total, dtype=np.int64)
        amask = np.ascontiguousarray(all_mask, dtype=np.int32)
        total_p = ptr(total, ctypes.c_int64)
        amask_p = ptr(amask, ctypes.c_int32)
    lib.hq_cut_scan(
        ptr(free, ctypes.c_int64),
        total_p,
        ptr(nt, ctypes.c_int64),
        ptr(life, ctypes.c_int32),
        ptr(needs, ctypes.c_int64),
        amask_p,
        ptr(sizes, ctypes.c_int64),
        ptr(mt, ctypes.c_int32),
        ptr(cm, ctypes.c_int32),
        ptr(oi, ctypes.c_int32),
        n_w, n_r, n_b, n_v, cm.shape[0],
        ptr(counts, ctypes.c_int32),
    )
    return counts


def native_nonzero(counts):
    """(flat_indices, values) of nonzero cells of an int32 ndarray in
    row-major order, or None when the native lib is unavailable. One C pass
    instead of numpy's nonzero + fancy-index gather."""
    lib = load_native()
    if lib is None:
        return None
    import numpy as np

    if counts.dtype != np.int32 or not counts.flags.c_contiguous:
        return None  # a copy here would eat the win; caller uses np.nonzero
    n = counts.size
    # nonzero cells are bounded by the number of (batch, worker) pairs the
    # water-fill can touch; start modest and retry on overflow
    capacity = min(n, 65536)
    while True:
        flat = np.empty(capacity, dtype=np.int64)
        vals = np.empty(capacity, dtype=np.int64)
        got = lib.hq_nonzero(
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n,
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            capacity,
        )
        if got < capacity or capacity >= n:
            return flat[:got], vals[:got]
        capacity = n


class NativeTaskQueue:
    """Same interface as scheduler.queues.TaskQueue, backed by C++."""

    __slots__ = ("_lib", "_handle")

    MAX_LEVELS = 4096

    def __init__(self, lib):
        self._lib = lib
        self._handle = ctypes.c_void_p(lib.hq_queue_new())

    def __del__(self):
        if getattr(self, "_handle", None):
            self._lib.hq_queue_free(self._handle)
            self._handle = None

    def __len__(self) -> int:
        return self._lib.hq_queue_len(self._handle)

    def add(self, priority, task_id: int) -> None:
        self._lib.hq_queue_add(self._handle, priority[0], priority[1], task_id)

    def remove(self, task_id: int) -> None:
        self._lib.hq_queue_remove(self._handle, task_id)

    def priority_sizes(self):
        n = self.MAX_LEVELS
        pu = (ctypes.c_int64 * n)()
        ps = (ctypes.c_int64 * n)()
        counts = (ctypes.c_int64 * n)()
        got = self._lib.hq_queue_priority_sizes(self._handle, pu, ps, counts, n)
        return [((pu[i], ps[i]), counts[i]) for i in range(got)]

    def take(self, priority, count: int):
        out = (ctypes.c_uint64 * count)()
        got = self._lib.hq_queue_take(
            self._handle, priority[0], priority[1], count, out
        )
        return [out[i] for i in range(got)]

    def all_tasks(self):
        n = len(self)
        out = (ctypes.c_uint64 * max(n, 1))()
        got = self._lib.hq_queue_all(self._handle, out, n)
        return [out[i] for i in range(got)]


def make_task_queue():
    """Factory: native queue if available, else the Python TaskQueue."""
    lib = load_native()
    if lib is not None:
        return NativeTaskQueue(lib)
    from hyperqueue_tpu.scheduler.queues import TaskQueue

    return TaskQueue()
