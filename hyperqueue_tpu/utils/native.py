"""ctypes loader for the native core (libhqcore.so).

Builds lazily via make on first use if the shared library is missing; falls
back silently to the pure-Python implementations when the toolchain is
unavailable (the Python and native structures share their semantics and the
test suite runs both).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from pathlib import Path

logger = logging.getLogger(__name__)

NATIVE_DIR = Path(__file__).resolve().parent.parent / "native"
LIB_PATH = NATIVE_DIR / "libhqcore.so"

_lib = None
_tried = False


def load_native():
    """Returns the ctypes lib or None."""
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("HQ_DISABLE_NATIVE"):
        return None
    try:
        import fcntl

        # concurrent processes (test server + workers) may race to build;
        # serialize via flock. make runs unconditionally — a fresh .so is a
        # no-op, and a STALE .so (built before a symbol was added) would
        # otherwise fail the prototype setup below
        with open(NATIVE_DIR / ".build.lock", "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            subprocess.run(
                ["make", "-C", str(NATIVE_DIR)],
                capture_output=True,
                timeout=120,
                check=True,
            )
    except (OSError, subprocess.CalledProcessError,
            subprocess.TimeoutExpired) as e:
        logger.debug("native build unavailable: %s", e)
        if not LIB_PATH.exists():
            return None
    try:
        lib = ctypes.CDLL(str(LIB_PATH))
        _set_prototypes(lib)
    except (OSError, AttributeError) as e:
        # AttributeError = a stale .so missing a newer symbol (make failed
        # or raced); fall back to the Python implementations
        logger.debug("native load failed: %s", e)
        return None
    _lib = lib
    return _lib


def _set_prototypes(lib) -> None:
    lib.hq_queue_new.restype = ctypes.c_void_p
    lib.hq_queue_free.argtypes = [ctypes.c_void_p]
    lib.hq_queue_add.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_uint64,
    ]
    lib.hq_queue_remove.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.hq_queue_len.argtypes = [ctypes.c_void_p]
    lib.hq_queue_len.restype = ctypes.c_int64
    lib.hq_queue_priority_sizes.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
    ]
    lib.hq_queue_priority_sizes.restype = ctypes.c_int64
    lib.hq_queue_take.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.hq_queue_take.restype = ctypes.c_int64
    lib.hq_queue_all.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64,
    ]
    lib.hq_queue_all.restype = ctypes.c_int64
    lib.hq_map_take.argtypes = [
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.hq_map_take.restype = ctypes.c_int64


class NativeTaskQueue:
    """Same interface as scheduler.queues.TaskQueue, backed by C++."""

    __slots__ = ("_lib", "_handle")

    MAX_LEVELS = 4096

    def __init__(self, lib):
        self._lib = lib
        self._handle = ctypes.c_void_p(lib.hq_queue_new())

    def __del__(self):
        if getattr(self, "_handle", None):
            self._lib.hq_queue_free(self._handle)
            self._handle = None

    def __len__(self) -> int:
        return self._lib.hq_queue_len(self._handle)

    def add(self, priority, task_id: int) -> None:
        self._lib.hq_queue_add(self._handle, priority[0], priority[1], task_id)

    def remove(self, task_id: int) -> None:
        self._lib.hq_queue_remove(self._handle, task_id)

    def priority_sizes(self):
        n = self.MAX_LEVELS
        pu = (ctypes.c_int64 * n)()
        ps = (ctypes.c_int64 * n)()
        counts = (ctypes.c_int64 * n)()
        got = self._lib.hq_queue_priority_sizes(self._handle, pu, ps, counts, n)
        return [((pu[i], ps[i]), counts[i]) for i in range(got)]

    def take(self, priority, count: int):
        out = (ctypes.c_uint64 * count)()
        got = self._lib.hq_queue_take(
            self._handle, priority[0], priority[1], count, out
        )
        return [out[i] for i in range(got)]

    def all_tasks(self):
        n = len(self)
        out = (ctypes.c_uint64 * max(n, 1))()
        got = self._lib.hq_queue_all(self._handle, out, n)
        return [out[i] for i in range(got)]


def make_task_queue():
    """Factory: native queue if available, else the Python TaskQueue."""
    lib = load_native()
    if lib is not None:
        return NativeTaskQueue(lib)
    from hyperqueue_tpu.scheduler.queues import TaskQueue

    return TaskQueue()
