"""Atomic shard leases for federated failover (ISSUE 11).

Each server shard holds a lease file (``lease.json``) in its shard dir and
renews it on a fixed cadence. A successor (warm standby or peer shard) may
claim a shard only when the lease has gone stale — the holder stopped
renewing for longer than the timeout, i.e. the process is dead or wedged.

Every lease mutation (claim, renew, release) runs under an exclusive
``flock`` on a sibling ``lease.lock`` file, making each one an atomic
read-check-write. The kernel releases the flock when the holding process
dies (kill -9 included), so a claimer that crashes mid-claim leaves
nothing to break; a SIGSTOPped process paused *inside* a renew keeps the
lock and simply delays the claim until it resumes or dies — which is the
correct outcome, because a paused-mid-write owner resuming later must not
be able to overwrite a successor's claim unseen. When two would-be
successors race for the same dead shard, exactly one takes the lock and
rewrites the lease; the loser backs off with ``LeaseRaceLost``.

Fencing: every successful acquire bumps the lease ``epoch``. The holder
re-reads the file under the lock on every renew — finding a different
owner (or epoch) means a successor claimed the shard while this process
was presumed dead (SIGSTOP, VM pause); the holder must stop immediately
instead of keeping a second scheduler + journal appender alive. The
fencing window is bounded by the renew interval; the journal's CRC
framing + seq numbers make anything written inside that window
detectable downstream.

A clean shutdown releases (removes) the lease, so watchers never promote
a successor for a shard an operator deliberately stopped.

Caveat: flock coordination is per-filesystem — all of a shard's
would-be owners must see the same (local or properly flock-supporting
shared) filesystem, the same assumption the server dir itself makes.
"""

from __future__ import annotations

import contextlib
import fcntl
import json
import logging
import os
import time
from pathlib import Path

from hyperqueue_tpu.events.journal import fsync_dir
from hyperqueue_tpu.utils import clock

logger = logging.getLogger("hq.lease")

LEASE_FILE = "lease.json"
LOCK_FILE = "lease.lock"


class LeaseError(Exception):
    pass


class LeaseHeldError(LeaseError):
    """The current holder is alive (fresh lease) — not claimable."""


class LeaseRaceLost(LeaseError):
    """Another claimer holds the lease lock right now — back off."""


class ShardLease:
    """One shard's lease: acquire (with stale takeover), renew, release.

    `timeout` is the staleness bound: a lease not renewed for `timeout`
    seconds is claimable. Renew on ~timeout/3 so one delayed write never
    looks like a death.
    """

    def __init__(self, shard_dir: Path, timeout: float = 15.0):
        self.shard_dir = Path(shard_dir)
        self.timeout = float(timeout)
        self.path = self.shard_dir / LEASE_FILE
        self.lock_path = self.shard_dir / LOCK_FILE
        self.owner: str | None = None
        self.epoch = 0

    # --- reads (lock-free: watchers poll these) -------------------------
    def read(self) -> dict | None:
        """Current lease record, or None (missing/torn — a torn record is
        a crashed writer, treated like no lease)."""
        try:
            with open(self.path) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except (ValueError, OSError):
            return None

    def state(self) -> str:
        """"absent" | "held" | "stale" — what a watcher sees."""
        record = self.read()
        if record is None:
            return "absent"
        age = clock.now() - float(record.get("renewed_at") or 0.0)
        return "stale" if age > self.timeout else "held"

    def age_seconds(self) -> float | None:
        record = self.read()
        if record is None:
            return None
        return max(clock.now() - float(record.get("renewed_at") or 0.0), 0.0)

    # --- writes (flock-serialized) --------------------------------------
    @contextlib.contextmanager
    def _locked(self):
        """Exclusive, non-blocking flock over every lease mutation: the
        read-check-write inside becomes atomic against other mutators.
        Released by the kernel if the holder dies — no debris to break."""
        self.shard_dir.mkdir(parents=True, exist_ok=True)
        fd = os.open(self.lock_path, os.O_CREAT | os.O_RDWR, 0o600)
        try:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                raise LeaseRaceLost(
                    f"lease lock busy at {self.lock_path}"
                ) from None
            yield
        finally:
            os.close(fd)  # closing the fd releases the flock

    def _write(self, record: dict) -> None:
        tmp = self.shard_dir / f".{LEASE_FILE}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(record, f)
            f.flush()
            os.fsync(f.fileno())
        tmp.replace(self.path)
        fsync_dir(self.shard_dir)

    def acquire(self, owner: str) -> dict:
        """Claim the shard for `owner`.

        Succeeds when the lease is absent (first boot) or stale (holder
        dead). Raises LeaseHeldError while the holder is alive and
        LeaseRaceLost when another mutator holds the lock — the caller
        backs off and retries (or gives up: the shard found its
        successor).
        """
        with self._locked():
            current = self.read()
            if current is not None and self.state() == "held" and (
                current.get("owner") != owner
            ):
                raise LeaseHeldError(
                    f"shard lease held by {current.get('owner')!r} "
                    f"(epoch {current.get('epoch')})"
                )
            record = {
                "owner": owner,
                "epoch": int((current or {}).get("epoch") or 0) + 1,
                "renewed_at": clock.now(),
                "pid": os.getpid(),
            }
            self._write(record)
        self.owner = owner
        self.epoch = record["epoch"]
        return record

    def renew(self) -> bool:
        """Refresh the holder's renewed_at stamp. Returns False when this
        holder has been FENCED: a successor claimed the shard (different
        owner, or a different epoch) — the caller must stop now. The
        check and the write share one flock, so a holder resuming from a
        long pause can never overwrite a successor's claim unseen."""
        if self.owner is None:
            raise LeaseError("renew() before acquire()")
        try:
            with self._locked():
                current = self.read()
                if current is not None and (
                    current.get("owner") != self.owner
                    or int(current.get("epoch") or 0) != self.epoch
                ):
                    return False
                self._write({
                    "owner": self.owner,
                    "epoch": self.epoch,
                    "renewed_at": clock.now(),
                    "pid": os.getpid(),
                })
            return True
        except LeaseRaceLost:
            # a claimer holds the lock RIGHT NOW — which only happens
            # when our lease already looks stale to it. Skip this renew;
            # the next one reads the claim's outcome and fences honestly.
            logger.warning(
                "lease lock busy during renew (a successor may be "
                "claiming); deferring to the next renewal"
            )
            return True

    def release(self) -> None:
        """Clean shutdown: retire the lease so watchers don't promote a
        successor for a deliberately-stopped shard. Only if this holder
        still owns it — a fenced instance must not delete its successor's
        lease."""
        if self.owner is None:
            return
        try:
            with self._locked():
                current = self.read()
                if current is not None and (
                    current.get("owner") == self.owner
                    and int(current.get("epoch") or 0) == self.epoch
                ):
                    try:
                        self.path.unlink()
                    except OSError:
                        pass
        except LeaseRaceLost:
            pass  # someone is claiming what they believe is stale: let them
        self.owner = None
