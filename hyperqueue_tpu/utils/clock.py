"""Process-wide clock provider: every wall/monotonic read behind one seam.

The server, scheduler, events, and autoalloc layers used to call
``time.time()`` / ``time.monotonic()`` directly at ~117 sites.  That was
fine for production but made the deterministic cluster simulator
(``hyperqueue_tpu/sim``) impossible: the simulator runs the REAL server
on a virtual-clock event loop where ten minutes of lease timeouts pass in
microseconds of wall time, so every timestamp the server records and every
staleness comparison it makes must come from the virtual clock — one code
path for sim and production, switched here.

Production pays one extra function call per read (the provider defaults to
the stdlib clocks); ``perf_counter`` is deliberately NOT routed — it
measures real CPU work for telemetry (tick phase latencies, fsync
histograms) and virtualizing it would make the simulator lie about its own
overhead.

Usage::

    from hyperqueue_tpu.utils import clock
    stamp = clock.now()        # wall clock (time.time)
    t0 = clock.monotonic()     # monotonic clock (time.monotonic)

A simulation installs its provider for the duration of a run::

    previous = clock.install(sim_clock)   # needs .time() and .monotonic()
    try: ...
    finally: clock.install(previous)
"""

from __future__ import annotations

import time as _time


class SystemClock:
    """The default provider: the stdlib clocks, no indirection beyond the
    method lookup."""

    __slots__ = ()

    time = staticmethod(_time.time)
    monotonic = staticmethod(_time.monotonic)


SYSTEM = SystemClock()
_provider = SYSTEM


def now() -> float:
    """Wall-clock seconds (``time.time`` under the active provider)."""
    return _provider.time()


def monotonic() -> float:
    """Monotonic seconds (``time.monotonic`` under the active provider)."""
    return _provider.monotonic()


def get() -> object:
    """The active provider."""
    return _provider


def install(provider) -> object:
    """Swap the process-wide provider; returns the previous one so the
    caller can restore it.  ``provider`` needs ``time()`` and
    ``monotonic()`` methods."""
    global _provider
    previous = _provider
    _provider = provider
    return previous


def reset() -> None:
    """Back to the stdlib clocks."""
    install(SYSTEM)


def is_simulated() -> bool:
    """True while a non-system provider is installed (the simulator)."""
    return _provider is not SYSTEM
