"""Shared value parsers used by both the CLI and TOML job files.

Reference: crates/hyperqueue/src/client/commands/submit/command.rs
parse_crash_limit — defs.rs deserialize_crash_limit reuses the same parser
so the CLI and job-file encodings can never drift.
"""

from __future__ import annotations

# Wire encoding (gateway.rs CrashLimit): positive = MaxCrashes,
# 0 = Unlimited, -1 = NeverRestart (fails on ANY worker loss while
# running, even clean stops — reactor.rs:166).
CRASH_LIMIT_NEVER_RESTART = -1
CRASH_LIMIT_UNLIMITED = 0


def parse_crash_limit(value, exc_type: type[Exception] = ValueError) -> int:
    """Positive integer, ``never-restart`` (-1) or ``unlimited`` (0)."""
    if isinstance(value, str):
        text = value.strip()
        if text == "never-restart":
            return CRASH_LIMIT_NEVER_RESTART
        if text == "unlimited":
            return CRASH_LIMIT_UNLIMITED
        try:
            value = int(text)
        except ValueError:
            raise exc_type(
                f"crash limit must be a positive integer, 'never-restart' "
                f"or 'unlimited', got {text!r}"
            ) from None
    limit = int(value)
    if limit == 0:
        # reference command.rs:1076 rejects 0 the same way
        raise exc_type(
            "crash limit cannot be 0; use 'never-restart' or 'unlimited' "
            "instead"
        )
    if limit < 0:
        raise exc_type(
            f"crash limit must be a positive integer, 'never-restart' or "
            f"'unlimited', got {value!r}"
        )
    return limit
