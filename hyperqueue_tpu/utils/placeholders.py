"""%{NAME} placeholder resolution in paths.

Reference: crates/hyperqueue/src/common/placeholders.rs:16-21,58-105 —
%{JOB_ID}, %{TASK_ID}, %{INSTANCE_ID}, %{SUBMIT_DIR}, %{SERVER_UID}, %{CWD}
resolved in cwd/stdout/stderr/stream paths. Unknown placeholders are left
intact (the reference warns; we do the same at debug level).
"""

from __future__ import annotations

import logging
import re

logger = logging.getLogger(__name__)

_PATTERN = re.compile(r"%\{([A-Z_]+)\}")


def fill_placeholders(template: str, mapping: dict[str, str]) -> str:
    def sub(match: re.Match) -> str:
        key = match.group(1)
        if key in mapping:
            return str(mapping[key])
        logger.debug("unknown placeholder %%{%s} left as-is", key)
        return match.group(0)

    return _PATTERN.sub(sub, template)


def task_placeholder_map(
    job_id: int,
    job_task_id: int,
    instance_id: int,
    submit_dir: str,
    server_uid: str,
    cwd: str | None = None,
) -> dict[str, str]:
    mapping = {
        "JOB_ID": str(job_id),
        "TASK_ID": str(job_task_id),
        "INSTANCE_ID": str(instance_id),
        "SUBMIT_DIR": submit_dir,
        "SERVER_UID": server_uid,
    }
    if cwd is not None:
        mapping["CWD"] = cwd
    return mapping
