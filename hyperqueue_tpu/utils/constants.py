"""Shared numeric constants (importable without pulling in JAX)."""

# "unlimited remaining lifetime" sentinel for worker time limits; fits int32
# so it can flow straight into the dense solver tensors.
INF_TIME = 2**31 - 1
