"""Process-wide metrics plane: registry, instruments, Prometheus exposition.

Reference: the reference factors every hot phase behind `trace_time!` spans
plus a dashboard event stream; production schedulers (Gavel, arXiv:2008.09213)
additionally presuppose scrapeable per-phase latency and utilization
telemetry. This module is the dependency-free substrate: counters, gauges and
fixed-bucket histograms with label support, rendered in the Prometheus text
exposition format (0.0.4) over a minimal asyncio HTTP endpoint
(`--metrics-port` on server and worker, off by default).

Design constraints:

- Zero hot-path cost when nothing scrapes: recording is a couple of dict
  lookups and float adds; anything expensive (walking server state, watchdog
  counters, per-worker fan-out) runs in *collect hooks* evaluated only at
  exposition time.
- Bounded memory: each metric caps its distinct label sets
  (``max_series``); series beyond the cap are dropped into a shared no-op
  series and counted in ``hq_metrics_dropped_series_total`` instead of
  growing without bound under a label-cardinality bug.
- One registry per process (one server or worker per process, like TRACER);
  ``snapshot()``/``export_samples()`` produce JSON-safe forms so worker
  metrics can piggyback on overview messages and the server can re-export a
  cluster-wide view with a ``worker`` label.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# Prometheus-conventional latency buckets (seconds), tuned one decade lower
# than the defaults: tick phases and spawn latencies live in the 0.1 ms-1 s
# range on this codebase's targets.
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

DEFAULT_MAX_SERIES = 64


def _escape_label_value(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _format_value(value: float) -> str:
    # integers render without a trailing .0 (matches prometheus client
    # output and keeps the golden test readable)
    if isinstance(value, float) and value.is_integer():
        return str(int(value))
    return repr(float(value))


def _labels_str(names: tuple[str, ...], values: tuple[str, ...],
                extra: str = "") -> str:
    parts = [
        f'{n}="{_escape_label_value(v)}"' for n, v in zip(names, values)
    ]
    if extra:
        parts.append(extra)
    if not parts:
        return ""
    return "{" + ",".join(parts) + "}"


class _CounterSeries:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount

    def set_total(self, value: float) -> None:
        """Adopt an externally-tracked monotonic total (e.g. watchdog
        failure counts maintained outside the registry)."""
        self.value = float(value)

    def reset(self) -> None:
        self.value = 0.0


class _GaugeSeries:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.value -= amount

    def reset(self) -> None:
        self.value = 0.0


class _HistogramSeries:
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: tuple[float, ...]):
        self.buckets = buckets
        self.counts = [0] * len(buckets)  # cumulative rendered at exposition
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        self.sum += value
        self.count += 1
        # per-bucket (non-cumulative) counts internally; cumulated on render
        for i, edge in enumerate(self.buckets):
            if value <= edge:
                self.counts[i] += 1
                return

    def reset(self) -> None:
        self.counts = [0] * len(self.buckets)
        self.sum = 0.0
        self.count = 0


class _NoopSeries:
    """Shared sink for label sets beyond the cardinality cap."""

    __slots__ = ()

    def inc(self, amount: float = 1.0) -> None: ...
    def dec(self, amount: float = 1.0) -> None: ...
    def set(self, value: float) -> None: ...
    def set_total(self, value: float) -> None: ...
    def observe(self, value: float) -> None: ...
    def reset(self) -> None: ...


_NOOP = _NoopSeries()


@dataclass
class Metric:
    name: str
    help: str
    type: str  # "counter" | "gauge" | "histogram"
    label_names: tuple[str, ...] = ()
    buckets: tuple[float, ...] = DEFAULT_BUCKETS
    max_series: int = DEFAULT_MAX_SERIES
    series: dict = field(default_factory=dict)  # label values -> series
    registry: "MetricsRegistry | None" = None

    def _make_series(self):
        if self.type == "counter":
            return _CounterSeries()
        if self.type == "gauge":
            return _GaugeSeries()
        return _HistogramSeries(self.buckets)

    def labels(self, *values, **kv):
        """Series for one label-value combination. Accepts positional values
        (in declaration order) or keyword form; values are stringified."""
        if kv:
            if values:
                raise ValueError("pass labels positionally or by name, not both")
            values = tuple(str(kv[n]) for n in self.label_names)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name} expects labels {self.label_names}, got {values}"
            )
        series = self.series.get(values)
        if series is None:
            if len(self.series) >= self.max_series:
                if self.registry is not None:
                    self.registry.dropped_series += 1
                return _NOOP
            series = self.series[values] = self._make_series()
        return series

    # label-less sugar: metric.inc()/set()/observe() on the () series
    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self.labels().dec(amount)

    def set(self, value: float) -> None:
        self.labels().set(value)

    def set_total(self, value: float) -> None:
        self.labels().set_total(value)

    def observe(self, value: float) -> None:
        self.labels().observe(value)

    def remove(self, *values) -> None:
        """Drop one series (e.g. a disconnected worker's gauges)."""
        self.series.pop(tuple(str(v) for v in values), None)

    def clear(self) -> None:
        """Drop every series (values AND label sets)."""
        self.series.clear()

    def reset(self) -> None:
        for series in self.series.values():
            series.reset()

    # --- rendering ------------------------------------------------------
    def render(self, out: list[str]) -> None:
        out.append(f"# HELP {self.name} {_escape_help(self.help)}")
        out.append(f"# TYPE {self.name} {self.type}")
        for values in sorted(self.series):
            series = self.series[values]
            if self.type == "histogram":
                cumulative = 0
                for edge, n in zip(self.buckets, series.counts):
                    cumulative += n
                    labels = _labels_str(
                        self.label_names, values, f'le="{_format_value(float(edge))}"'
                    )
                    out.append(f"{self.name}_bucket{labels} {cumulative}")
                labels = _labels_str(self.label_names, values, 'le="+Inf"')
                out.append(f"{self.name}_bucket{labels} {series.count}")
                labels = _labels_str(self.label_names, values)
                out.append(f"{self.name}_sum{labels} {_format_value(series.sum)}")
                out.append(f"{self.name}_count{labels} {series.count}")
            else:
                labels = _labels_str(self.label_names, values)
                out.append(
                    f"{self.name}{labels} {_format_value(series.value)}"
                )


class MetricsRegistry:
    def __init__(self):
        self._metrics: dict[str, Metric] = {}
        self._collect_hooks: list = []
        self.dropped_series = 0

    # --- registration (get-or-create; name is the identity) -------------
    def _get_or_create(self, name: str, help: str, type: str,
                       labels: tuple[str, ...], **kw) -> Metric:
        metric = self._metrics.get(name)
        if metric is not None:
            if metric.type != type:
                raise ValueError(
                    f"metric {name} already registered as {metric.type}"
                )
            return metric
        metric = Metric(
            name=name, help=help, type=type,
            label_names=tuple(labels), registry=self, **kw,
        )
        self._metrics[name] = metric
        return metric

    def counter(self, name: str, help: str = "",
                labels: tuple[str, ...] = (),
                max_series: int = DEFAULT_MAX_SERIES) -> Metric:
        return self._get_or_create(name, help, "counter", labels,
                                   max_series=max_series)

    def gauge(self, name: str, help: str = "",
              labels: tuple[str, ...] = (),
              max_series: int = DEFAULT_MAX_SERIES) -> Metric:
        return self._get_or_create(name, help, "gauge", labels,
                                   max_series=max_series)

    def histogram(self, name: str, help: str = "",
                  labels: tuple[str, ...] = (),
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS,
                  max_series: int = DEFAULT_MAX_SERIES) -> Metric:
        metric = self._get_or_create(name, help, "histogram", labels,
                                     buckets=tuple(buckets),
                                     max_series=max_series)
        return metric

    def get(self, name: str) -> Metric | None:
        return self._metrics.get(name)

    def add_collect_hook(self, fn) -> None:
        """fn() runs before every render/snapshot — the place to refresh
        gauges from live state (queue depths, watchdog counters, per-worker
        fan-out) without touching any hot path."""
        self._collect_hooks.append(fn)

    def remove_collect_hook(self, fn) -> None:
        if fn in self._collect_hooks:
            self._collect_hooks.remove(fn)

    def _collect(self) -> None:
        for fn in self._collect_hooks:
            try:
                fn()
            except Exception:  # noqa: BLE001 - a bad hook must not break scrapes
                import logging

                logging.getLogger("hq.metrics").exception(
                    "metrics collect hook failed"
                )

    # --- output ---------------------------------------------------------
    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        self._collect()
        out: list[str] = []
        drops = self._metrics.get("hq_metrics_dropped_series_total")
        if self.dropped_series and drops is None:
            drops = self.counter(
                "hq_metrics_dropped_series_total",
                "label sets dropped by the per-metric cardinality cap",
            )
        if drops is not None:
            drops.labels().set_total(self.dropped_series)
        for name in sorted(self._metrics):
            self._metrics[name].render(out)
        return "\n".join(out) + "\n"

    def export_samples(self, prefix: str = "",
                       types: tuple[str, ...] = ("gauge", "counter"),
                       collect: bool = True) -> list[dict]:
        """JSON-safe scalar samples (no histograms), for piggybacking worker
        metrics on overview messages. Each: {name, type, labels, value} —
        deliberately NO help text: these ride on every overview of every
        worker and get journaled verbatim, so each repeated byte is journal
        growth and replay time."""
        if collect:
            self._collect()
        out = []
        for name in sorted(self._metrics):
            metric = self._metrics[name]
            if metric.type not in types or not name.startswith(prefix):
                continue
            for values, series in metric.series.items():
                out.append({
                    "name": name,
                    "type": metric.type,
                    "labels": dict(zip(metric.label_names, values)),
                    "value": series.value,
                })
        return out

    def snapshot(self) -> dict:
        """Full JSON-ready dump (histograms included) for debug RPCs."""
        self._collect()
        out: dict = {}
        for name in sorted(self._metrics):
            metric = self._metrics[name]
            series_out = []
            for values, series in sorted(metric.series.items()):
                entry: dict = {"labels": dict(zip(metric.label_names, values))}
                if metric.type == "histogram":
                    entry["count"] = series.count
                    entry["sum"] = round(series.sum, 6)
                    entry["buckets"] = dict(
                        zip((str(b) for b in metric.buckets), series.counts)
                    )
                else:
                    entry["value"] = series.value
                series_out.append(entry)
            out[name] = {"type": metric.type, "series": series_out}
        return out

    def reset(self) -> None:
        """Zero every series value (registrations and label sets survive so
        module-level instrument handles stay valid). The benchmark hook:
        reset, run a steady-state window, scrape."""
        for metric in self._metrics.values():
            metric.reset()
        self.dropped_series = 0


# process-wide registry (one server or worker per process, like TRACER)
REGISTRY = MetricsRegistry()


# ---------------------------------------------------------------- scrape I/O
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


async def start_exposition_server(render, port: int, host: str = "0.0.0.0",
                                  probes: dict | None = None):
    """Serve GET /metrics on (host, port), answering with render()'s text
    (render may be sync or async). Returns (asyncio server, bound port) —
    pass port 0 for an ephemeral port (tests/CI).

    ``probes`` maps extra paths (``/healthz``, ``/readyz``) to callables
    returning ``(ok, detail_dict)``; they answer 200/503 with a JSON body
    (ISSUE 18 health plane). Probes served off the same loop as the
    process's reactor are truthful by construction: a wedged loop cannot
    answer at all, which is the failure an orchestrator treats as down.

    Deliberately minimal HTTP/1.0-style handling: read the request head,
    answer one response, close. A metrics endpoint needs no keep-alive, no
    TLS, no routing beyond /metrics and the probe paths."""
    import asyncio
    import inspect
    import json

    async def handle(reader, writer):
        try:
            request = await asyncio.wait_for(reader.readline(), timeout=5.0)
            while True:
                line = await asyncio.wait_for(reader.readline(), timeout=5.0)
                if not line.strip():
                    break
            parts = request.split()
            path = parts[1].decode("latin-1") if len(parts) > 1 else "/"
            path = path.split("?")[0]
            if probes and path in probes:
                try:
                    ok, detail = probes[path]()
                except Exception:  # noqa: BLE001 - a broken check IS unready
                    ok, detail = False, {"error": "probe raised"}
                body = (
                    json.dumps({"ok": bool(ok), **(detail or {})},
                               sort_keys=True) + "\n"
                ).encode("utf-8")
                status = "200 OK" if ok else "503 Service Unavailable"
                head = (
                    f"HTTP/1.1 {status}\r\n"
                    "Content-Type: application/json\r\n"
                    f"Content-Length: {len(body)}\r\n"
                    "Connection: close\r\n\r\n"
                )
            elif path in ("/", "/metrics"):
                text = render()
                if inspect.isawaitable(text):
                    text = await text
                body = text.encode("utf-8")
                head = (
                    "HTTP/1.1 200 OK\r\n"
                    f"Content-Type: {CONTENT_TYPE}\r\n"
                    f"Content-Length: {len(body)}\r\n"
                    "Connection: close\r\n\r\n"
                )
            else:
                body = b"not found\n"
                head = (
                    "HTTP/1.1 404 Not Found\r\n"
                    "Content-Type: text/plain\r\n"
                    f"Content-Length: {len(body)}\r\n"
                    "Connection: close\r\n\r\n"
                )
            writer.write(head.encode("latin-1") + body)
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    server = await asyncio.start_server(handle, host, port)
    bound = server.sockets[0].getsockname()[1]
    return server, bound


async def start_metrics_server(registry: MetricsRegistry, port: int,
                               host: str = "0.0.0.0",
                               probes: dict | None = None):
    """Serve a registry's exposition on (host, port); see
    start_exposition_server."""
    return await start_exposition_server(registry.render, port, host,
                                         probes=probes)


def probe(host: str, port: int, path: str = "/readyz",
          timeout: float = 5.0) -> tuple[int, dict]:
    """Blocking one-shot health-probe request (test/bench helper).
    Returns (http_status, parsed JSON body) — unlike :func:`scrape` a
    503 is a RESULT here, not an error."""
    import json
    import socket

    with socket.create_connection((host, port), timeout=timeout) as sock:
        sock.sendall(
            f"GET {path} HTTP/1.1\r\nHost: {host}\r\n"
            "Connection: close\r\n\r\n".encode("latin-1")
        )
        chunks = []
        while True:
            data = sock.recv(65536)
            if not data:
                break
            chunks.append(data)
    raw = b"".join(chunks)
    head, _, body = raw.partition(b"\r\n\r\n")
    status_line = head.split(b"\r\n", 1)[0].split()
    status = int(status_line[1]) if len(status_line) > 1 else 0
    try:
        payload = json.loads(body.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        payload = {}
    return status, payload


def scrape(host: str, port: int, timeout: float = 5.0) -> str:
    """Blocking one-shot scrape of a metrics endpoint (bench/test helper;
    no client library required)."""
    import socket

    with socket.create_connection((host, port), timeout=timeout) as sock:
        sock.sendall(
            f"GET /metrics HTTP/1.1\r\nHost: {host}\r\n"
            "Connection: close\r\n\r\n".encode("latin-1")
        )
        chunks = []
        while True:
            data = sock.recv(65536)
            if not data:
                break
            chunks.append(data)
    raw = b"".join(chunks)
    head, _, body = raw.partition(b"\r\n\r\n")
    if not head.startswith(b"HTTP/1.1 200"):
        raise ConnectionError(
            f"metrics scrape failed: {head.splitlines()[0:1]}"
        )
    return body.decode("utf-8")


def parse_exposition(text: str) -> dict:
    """Parse Prometheus text format into {name: {type, samples}} where
    samples is {(sample_name, frozenset(labels.items())): value}. Used by
    the golden/e2e tests and `bench.py --metrics` — a real parser would be
    a dependency; this handles exactly what `render` emits."""
    out: dict = {}
    types: dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split(None, 3)
            types[name] = mtype
            out.setdefault(name, {"type": mtype, "samples": {}})
            continue
        if line.startswith("#"):
            continue
        # sample: name{labels} value
        if "{" in line:
            name, rest = line.split("{", 1)
            labels_raw, _, value = rest.rpartition("} ")
            labels = {}
            # label values produced by render never contain unescaped
            # commas inside quotes in our usage; keep the split simple but
            # honor escaped quotes
            for part in _split_labels(labels_raw):
                k, _, v = part.partition("=")
                labels[k] = _unescape_label_value(v.strip('"'))
        else:
            name, _, value = line.rpartition(" ")
            labels = {}
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if base.endswith(suffix) and base[: -len(suffix)] in types:
                base = base[: -len(suffix)]
                break
        entry = out.setdefault(
            base, {"type": types.get(base, "untyped"), "samples": {}}
        )
        entry["samples"][(name, frozenset(labels.items()))] = float(value)
    return out


def _unescape_label_value(value: str) -> str:
    """Inverse of _escape_label_value, processed left-to-right in ONE pass:
    chained str.replace would misread an escaped backslash followed by `n`
    (the sequence \\\\n) as an escaped newline."""
    out: list[str] = []
    i = 0
    while i < len(value):
        ch = value[i]
        if ch == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt in ('"', "\\"):
                out.append(nxt)
                i += 2
                continue
        out.append(ch)
        i += 1
    return "".join(out)


def _split_labels(raw: str) -> list[str]:
    parts, buf, in_quotes, escaped = [], [], False, False
    for ch in raw:
        if escaped:
            buf.append(ch)
            escaped = False
            continue
        if ch == "\\":
            buf.append(ch)
            escaped = True
            continue
        if ch == '"':
            in_quotes = not in_quotes
            buf.append(ch)
            continue
        if ch == "," and not in_quotes:
            parts.append("".join(buf))
            buf = []
            continue
        buf.append(ch)
    if buf:
        parts.append("".join(buf))
    return [p for p in (s.strip() for s in parts) if p]


def relabel_exposition(text: str, label: str, value: str) -> list[tuple]:
    """Parse one Prometheus text exposition into
    ``[(metric_name, help_line, type_line, [sample_line, ...]), ...]``
    with ``label="value"`` injected into every sample line.

    This is the fleet metrics proxy's building block (ISSUE 15): each
    shard's exposition is re-labelled with its shard id, then
    ``merge_expositions`` regroups the per-shard fragments so every
    metric's samples sit under ONE HELP/TYPE header (the text format
    forbids a metric appearing twice). Text-level on purpose — values
    round-trip byte-exact, no float re-formatting."""
    groups: list[tuple] = []
    current: list | None = None
    types: dict[str, str] = {}
    injected = f'{label}="{_escape_label_value(value)}"'
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            name = line.split(None, 3)[2]
            current = [name, line, None, []]
            groups.append(current)
            continue
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split(None, 3)
            types[name] = mtype
            if current is None or current[0] != name:
                current = [name, None, line, []]
                groups.append(current)
            else:
                current[2] = line
            continue
        if line.startswith("#"):
            continue
        if "{" in line:
            head, rest = line.split("{", 1)
            line = f"{head}{{{injected}," + rest
        else:
            sample_name, _, sample_value = line.rpartition(" ")
            line = f"{sample_name}{{{injected}}} {sample_value}"
        # _bucket/_sum/_count samples belong to their histogram's group
        sample_base = line.split("{", 1)[0]
        base = sample_base
        for suffix in ("_bucket", "_sum", "_count"):
            if base.endswith(suffix) and base[: -len(suffix)] in types:
                base = base[: -len(suffix)]
                break
        if current is None or current[0] != base:
            current = next((g for g in groups if g[0] == base), None)
            if current is None:
                current = [base, None, None, []]
                groups.append(current)
        current[3].append(line)
    return [tuple(g) for g in groups]


def merge_expositions(shard_texts: dict[str, str],
                      label: str = "shard",
                      exclude: frozenset = frozenset()) -> str:
    """One fleet-wide exposition from per-shard scrapes: every sample
    gains ``label="<shard>"`` and same-named metrics across shards merge
    under a single HELP/TYPE header (required by the text format). Shard
    order in the dict decides whose HELP text wins ties (they are
    identical across shards in practice). ``exclude`` drops metrics the
    caller synthesizes itself (the proxy's shard_up rows — a shard
    running a --failover-watch scan exports its OWN shard-labelled
    copies, which would collide with the injected label)."""
    merged: dict[str, list] = {}
    for shard, text in shard_texts.items():
        for name, help_line, type_line, samples in relabel_exposition(
            text, label, str(shard)
        ):
            if name in exclude:
                continue
            entry = merged.get(name)
            if entry is None:
                entry = merged[name] = [help_line, type_line, []]
            else:
                entry[0] = entry[0] or help_line
                entry[1] = entry[1] or type_line
            entry[2].extend(samples)
    out: list[str] = []
    for name in sorted(merged):
        help_line, type_line, samples = merged[name]
        if help_line:
            out.append(help_line)
        if type_line:
            out.append(type_line)
        out.extend(samples)
    return "\n".join(out) + "\n"


def histogram_summary(parsed: dict, name: str) -> dict:
    """Per-label-set {count, sum, mean, p50~, p95~, max_bucket} summary of a
    parsed histogram — percentile estimates from the cumulative bucket
    counts (upper bucket edge of the quantile's bucket). Feeds
    `bench.py --metrics` and `hq job timeline`-adjacent tooling."""
    entry = parsed.get(name)
    if not entry or entry["type"] != "histogram":
        return {}
    # regroup samples by label set (minus `le`)
    series: dict = {}
    for (sample, labels), value in entry["samples"].items():
        base_labels = frozenset(
            (k, v) for k, v in labels if k != "le"
        )
        bucket = series.setdefault(
            base_labels, {"buckets": [], "sum": 0.0, "count": 0.0}
        )
        le = dict(labels).get("le")
        if sample.endswith("_bucket") and le is not None:
            edge = float("inf") if le == "+Inf" else float(le)
            bucket["buckets"].append((edge, value))
        elif sample.endswith("_sum"):
            bucket["sum"] = value
        elif sample.endswith("_count"):
            bucket["count"] = value
    out = {}
    for base_labels, data in series.items():
        buckets = sorted(data["buckets"])
        count = data["count"]

        def quantile(q):
            if not count:
                return 0.0
            target = q * count
            for edge, cumulative in buckets:
                if cumulative >= target:
                    return edge
            return buckets[-1][0] if buckets else 0.0

        key = ",".join(
            f"{k}={v}" for k, v in sorted(base_labels)
        ) or "_"

        def finite(edge):
            # JSON-safe: the +Inf bucket becomes null ("beyond the largest
            # finite bucket") instead of json.dumps's non-RFC Infinity
            return edge if edge != float("inf") else None

        out[key] = {
            "count": int(count),
            "sum": round(data["sum"], 6),
            "mean": round(data["sum"] / count, 6) if count else 0.0,
            "p50_le": finite(quantile(0.50)),
            "p95_le": finite(quantile(0.95)),
        }
    return out
