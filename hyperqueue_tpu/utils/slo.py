"""Declarative SLOs with multi-window burn-rate alerting (ISSUE 18).

The metrics plane (utils/metrics.py) records what happened; this module
judges it. An :class:`SloSpec` names a served-level objective over an
existing instrument — "95% of ticks complete under 250 ms", "99.9% of
shards are up" — and :class:`SloEngine` evaluates every spec on a
sliding window over the process registry, converting bad-event
fractions into **burn rates** (how many times faster than sustainable
the error budget is being consumed; SRE workbook chapter 5).

An alert fires when the burn rate exceeds a rule's factor on BOTH its
long and short window — the long window proves the problem is real,
the short window proves it is still happening (and resolves the alert
promptly once it stops). Two severities ship by default:

- ``page``  — 14.4x burn over 1 h + 5 m (budget gone in ~2 days)
- ``ticket`` — 6x burn over 6 h + 30 m (budget gone in ~5 days)

All windows scale by ``HQ_SLO_WINDOW_SCALE`` so the simulator (virtual
clock) and the bench smoke can compress hours into seconds without
touching the math. Evaluation is O(specs x rules) per tick and reads
only cumulative counters, so it is cheap enough to run everywhere the
registry lives: server reactor loop, standby watcher, simulator.

Alert *transitions* are the integration surface: ``evaluate`` returns
them, the server journals each as an ``slo-alert`` event (riding the
subscribe plane and the FleetFeed), and the exported gauges
``hq_slo_{error_budget_remaining,burn_rate,alerts_firing}`` expose the
same judgement to scrapers.
"""

from __future__ import annotations

import os
from collections import deque
from dataclasses import dataclass, field

from hyperqueue_tpu.utils import clock
from hyperqueue_tpu.utils.metrics import REGISTRY

# exported judgement (module-level like every other instrument so the
# docs catalog checker sees the literal registrations)
_BUDGET_REMAINING = REGISTRY.gauge(
    "hq_slo_error_budget_remaining",
    "fraction of the SLO's error budget left over its longest alert "
    "window (1 = untouched, 0 = exhausted, negative = overdrawn)",
    labels=("slo",),
)
_BURN_RATE = REGISTRY.gauge(
    "hq_slo_burn_rate",
    "error-budget burn rate per SLO and window (1 = exactly "
    "sustainable, 14.4 = page-level burn)",
    labels=("slo", "window"),
)
_ALERTS_FIRING = REGISTRY.gauge(
    "hq_slo_alerts_firing",
    "SLO burn-rate alerts currently firing, by severity",
    labels=("severity",),
)


@dataclass(frozen=True)
class SloSpec:
    """One objective over one instrument.

    kind "latency": ``metric`` is a histogram; an observation is good
    when it lands in a bucket whose upper edge is <= ``threshold``.
    kind "availability": ``metric`` is a 0/1 gauge family; each
    evaluation tick scores every series (good = value >= 1).
    """

    name: str
    description: str
    metric: str
    objective: float
    kind: str = "latency"
    threshold: float = 0.0
    labels: tuple = ()  # ((label, value), ...) filter on series

    @property
    def budget(self) -> float:
        return max(1.0 - self.objective, 1e-9)


@dataclass(frozen=True)
class BurnRule:
    severity: str
    factor: float
    long_s: float
    short_s: float


DEFAULT_RULES = (
    BurnRule("page", 14.4, 3600.0, 300.0),
    BurnRule("ticket", 6.0, 21600.0, 1800.0),
)

DEFAULT_SPECS = (
    SloSpec(
        name="tick-latency",
        description="95% of scheduler ticks complete under 250 ms",
        metric="hq_tick_phase_seconds",
        labels=(("phase", "total"),),
        objective=0.95,
        threshold=0.25,
    ),
    SloSpec(
        name="submit-ack",
        description="99% of client RPCs leave the reactor handoff "
                    "within 500 ms",
        metric="hq_reactor_lag_seconds",
        labels=(("plane", "rpc"),),
        objective=0.99,
        threshold=0.5,
    ),
    SloSpec(
        name="queue-age",
        description="95% of tasks are assigned within 60 s of "
                    "becoming ready",
        metric="hq_task_queue_age_seconds",
        objective=0.95,
        threshold=60.0,
    ),
    SloSpec(
        name="restore-duration",
        description="99% of journal restores finish under 30 s",
        metric="hq_restore_duration_seconds",
        objective=0.99,
        threshold=30.0,
    ),
    SloSpec(
        name="shard-availability",
        description="99.9% shard liveness as seen by the failover "
                    "watcher's lease scan",
        metric="hq_federation_shard_up",
        kind="availability",
        objective=0.999,
    ),
)


def alert_names(specs=DEFAULT_SPECS, rules=DEFAULT_RULES) -> list[str]:
    """Every alert name this engine can emit (``<slo>:<severity>``) —
    the docs catalog checker fails on any of these missing from
    docs/observability.md, mirroring the metric-name checker."""
    return [f"{s.name}:{r.severity}" for s in specs for r in rules]


def window_scale() -> float:
    """HQ_SLO_WINDOW_SCALE compresses every alert window (sim/bench:
    hours become seconds without changing the burn-rate math)."""
    try:
        scale = float(os.environ.get("HQ_SLO_WINDOW_SCALE", "") or 1.0)
    except ValueError:
        scale = 1.0
    return scale if scale > 0 else 1.0


@dataclass
class _SpecState:
    # ring of (monotonic time, cumulative good, cumulative total)
    ring: deque = field(default_factory=lambda: deque(maxlen=4096))
    # availability specs accumulate their own cumulative counts
    # (gauges have no history; each evaluation tick scores the fleet)
    cum_good: float = 0.0
    cum_total: float = 0.0


class SloEngine:
    """Evaluates specs against the process registry; owns alert state.

    One instance per process (server, standby watcher, sim server) —
    construction is cheap and ``evaluate`` no-ops for specs whose
    metric has no data yet, so a worker-less standby only ever scores
    shard availability."""

    def __init__(self, registry=None, specs=DEFAULT_SPECS,
                 rules=DEFAULT_RULES, scale: float | None = None):
        self.registry = registry if registry is not None else REGISTRY
        self.specs = tuple(specs)
        self.rules = tuple(rules)
        self.scale = scale if scale is not None else window_scale()
        # evaluation cadence: ~1/10th of the shortest short window,
        # bounded to stay responsive in scaled-down runs and cheap in
        # production (15 s ticks for the default 5 m short window)
        shortest = min((r.short_s for r in self.rules), default=300.0)
        self.interval = min(15.0, max(0.05, shortest * self.scale / 10))
        self._state: dict[str, _SpecState] = {}
        self._firing: dict[tuple[str, str], dict] = {}
        self.history: deque = deque(maxlen=64)
        self.last_eval = 0.0

    # ------------------------------------------------------------ read
    def _read(self, spec: SloSpec) -> tuple[float, float] | None:
        metric = self.registry.get(spec.metric)
        if metric is None or not metric.series:
            return None
        want = dict(spec.labels)
        if spec.kind == "availability":
            up = total = 0.0
            for values, series in metric.series.items():
                sample = dict(zip(metric.label_names, values))
                if any(sample.get(k) != v for k, v in want.items()):
                    continue
                total += 1.0
                if series.value >= 1.0:
                    up += 1.0
            if total == 0.0:
                return None
            state = self._state.setdefault(spec.name, _SpecState())
            state.cum_good += up
            state.cum_total += total
            return state.cum_good, state.cum_total
        good = total = 0.0
        matched = False
        for values, series in metric.series.items():
            sample = dict(zip(metric.label_names, values))
            if any(sample.get(k) != v for k, v in want.items()):
                continue
            matched = True
            total += series.count
            for edge, n in zip(series.buckets, series.counts):
                if edge <= spec.threshold:
                    good += n
        if not matched:
            return None
        return good, total

    # -------------------------------------------------------- evaluate
    def evaluate(self, now: float | None = None) -> list[dict]:
        """One evaluation tick: sample every spec, update alert state,
        refresh the exported gauges. Returns the alert TRANSITIONS this
        tick (state "firing" or "resolved") for the caller to journal."""
        if now is None:
            now = clock.monotonic()
        self.last_eval = now
        transitions: list[dict] = []
        for spec in self.specs:
            reading = self._read(spec)
            state = self._state.setdefault(spec.name, _SpecState())
            if reading is None:
                continue
            state.ring.append((now, reading[0], reading[1]))
            longest = 0.0
            for rule in self.rules:
                long_w = rule.long_s * self.scale
                short_w = rule.short_s * self.scale
                burn_long = self._burn(state.ring, spec, now, long_w)
                burn_short = self._burn(state.ring, spec, now, short_w)
                if long_w > longest:
                    # budget remaining over the LONGEST window: burn 1.0
                    # sustained for the whole window consumes it exactly
                    longest = long_w
                    _BUDGET_REMAINING.labels(spec.name).set(
                        round(1.0 - burn_long, 6)
                    )
                _BURN_RATE.labels(spec.name, _wname(rule.long_s)).set(
                    round(burn_long, 6)
                )
                _BURN_RATE.labels(spec.name, _wname(rule.short_s)).set(
                    round(burn_short, 6)
                )
                key = (spec.name, rule.severity)
                firing = key in self._firing
                should_fire = (
                    burn_long >= rule.factor and burn_short >= rule.factor
                )
                if should_fire and not firing:
                    alert = {
                        "alert": f"{spec.name}:{rule.severity}",
                        "slo": spec.name,
                        "severity": rule.severity,
                        "state": "firing",
                        "since": now,
                        "burn_rate": round(burn_long, 3),
                        "burn_short": round(burn_short, 3),
                        "window": [long_w, short_w],
                        "objective": spec.objective,
                        "description": spec.description,
                    }
                    self._firing[key] = alert
                    self.history.append(dict(alert))
                    transitions.append(dict(alert))
                elif firing and not should_fire:
                    alert = self._firing.pop(key)
                    resolved = dict(alert)
                    resolved["state"] = "resolved"
                    resolved["burn_rate"] = round(burn_long, 3)
                    resolved["burn_short"] = round(burn_short, 3)
                    resolved["fired_for"] = round(
                        max(now - alert["since"], 0.0), 3
                    )
                    self.history.append(dict(resolved))
                    transitions.append(resolved)
                elif firing:
                    live = self._firing[key]
                    live["burn_rate"] = round(burn_long, 3)
                    live["burn_short"] = round(burn_short, 3)
        by_severity: dict[str, int] = {
            r.severity: 0 for r in self.rules
        }
        for (_, severity) in self._firing:
            by_severity[severity] = by_severity.get(severity, 0) + 1
        for severity, count in by_severity.items():
            _ALERTS_FIRING.labels(severity).set(count)
        return transitions

    @staticmethod
    def _burn(ring, spec: SloSpec, now: float, window: float) -> float:
        """Burn rate over one window: (bad fraction) / (error budget).
        The baseline is the newest sample at or before the window start
        — or the oldest sample while the ring is still shorter than the
        window (fraction-based, so a short actual span stays honest)."""
        if not ring:
            return 0.0
        start = now - window
        baseline = ring[0]
        for sample in reversed(ring):
            if sample[0] <= start:
                baseline = sample
                break
        head = ring[-1]
        d_total = head[2] - baseline[2]
        if d_total <= 0.0:
            return 0.0
        d_bad = d_total - (head[1] - baseline[1])
        return (d_bad / d_total) / spec.budget

    # ----------------------------------------------------------- state
    def alerts(self) -> dict:
        """Wire shape for the `hq alerts` RPC: currently-firing alerts
        plus the recent transition history (newest last)."""
        return {
            "firing": [dict(a) for a in self._firing.values()],
            "recent": [dict(a) for a in self.history],
            "interval": self.interval,
            "scale": self.scale,
        }

    def badge(self) -> dict:
        """Tiny firing summary for sample blocks / `hq top`: count plus
        the worst severity currently firing (page > ticket)."""
        severities = [a.get("severity") for a in self._firing.values()]
        worst = None
        if "page" in severities:
            worst = "page"
        elif severities:
            worst = sorted(severities)[0]
        return {"firing": len(self._firing), "worst": worst}

    def paging_alerts(self) -> list[dict]:
        """Firing page-severity alerts — the readiness-probe and
        autoalloc-quarantine input."""
        return [
            dict(a) for a in self._firing.values()
            if a.get("severity") == "page"
        ]

    def reset(self) -> None:
        """Drop every window and alert (mirrors LagTracker.reset on
        `hq server reset-metrics`): the next steady-state measurement
        window starts clean instead of inheriting a breach."""
        self._state.clear()
        self._firing.clear()
        self.history.clear()
        for severity in {r.severity for r in self.rules}:
            _ALERTS_FIRING.labels(severity).set(0)


def _wname(seconds: float) -> str:
    """Stable window label from the UNscaled rule duration (scaled runs
    keep the production series names)."""
    if seconds >= 3600:
        return f"{seconds / 3600:g}h"
    if seconds >= 60:
        return f"{seconds / 60:g}m"
    return f"{seconds:g}s"
