"""Journaled job-ownership map for elastic resharding (ISSUE 17).

The federation's original routing rule — shard k of N owns every job id
with ``(job_id - 1) % N == k`` — is static arithmetic: nothing can move.
This module adds the dynamic layer on top: an append-only, epoch-fenced
**ownership log** at the federation root (``ownership.log``) that records
job migrations and online shard additions. Routing becomes a three-level
resolution, in precedence order:

1. an explicit assignment from a committed migration record,
2. the added-shard id-block rule (shards added online allocate job ids
   from reserved high blocks, see :data:`ADDED_ID_BASE`),
3. the modulo partition frozen at ``base_shard_count`` — the shard count
   the federation booted with, which never changes even as shards are
   added (pre-existing job ids must keep routing to their journals).

Durability discipline mirrors ``utils/lease.py``: writers serialize
through a flock on ``.ownership.lock`` and fsync every append; readers
are lock-free and tolerate a torn final line (the kill -9 artifact —
an append that never completed simply never happened). Every appended
record carries a monotonically increasing ``epoch``; the epoch is the
fencing token the whole migration protocol hangs off.

Record kinds (one JSON object per line):

``migration-intent``   a migration ``mig`` of ``job`` from shard
                       ``from`` to ``to`` has been claimed. At most one
                       in-flight intent may exist per job (double claims
                       raise :class:`MigrationClaimed`). Ownership is
                       UNCHANGED — the source still owns the job.
``migration-commit``   the destination durably imported the job: this
                       line is the linearization point of the ownership
                       transfer. From here the destination owns the job
                       no matter who crashes.
``migration-done``     the source dropped its sealed copy; the migration
                       is fully retired.
``migration-abort``    the migration was abandoned before commit; the
                       source keeps the job.
``shard-add``          shard ``shard`` joined online; carries the new
                       ``shard_count`` and the shard's reserved job-id
                       block base.
``rebalance``          a coordinator rebalance verdict (moved / held /
                       why) — pure observability, no routing effect.
"""

from __future__ import annotations

import fcntl
import json
import os
from contextlib import contextmanager
from dataclasses import dataclass, field
from pathlib import Path

from hyperqueue_tpu.utils import clock

OWNERSHIP_FILE = "ownership.log"
LOCK_FILE = ".ownership.lock"

# Shards added online allocate job ids from reserved high blocks so the
# id alone still routes (the whole point of the original partition): the
# base shards' strided counters live in low id space, added shard k
# (k >= base_shard_count) owns ids in
#   (ADDED_ID_BASE + (k - base)*SPAN, ADDED_ID_BASE + (k - base + 1)*SPAN].
# ids.make_task_id caps job ids at 2^32 - 1, so the scheme supports
# ~4030 added shards of ~1M jobs each; a base shard would need 2^26
# jobs (at its stride) to ever collide with the reserved region.
ADDED_ID_BASE = 1 << 26
ADDED_ID_SPAN = 1 << 20


class OwnershipError(RuntimeError):
    """Malformed or inconsistent ownership-log operation."""


class MigrationClaimed(OwnershipError):
    """A different in-flight migration already claims this job."""


def added_shard_block(shard_id: int, base_shard_count: int) -> tuple[int, int]:
    """Job-id block ``(lo, hi]`` reserved for an added shard."""
    idx = int(shard_id) - int(base_shard_count)
    if idx < 0:
        raise OwnershipError(
            f"shard {shard_id} is a base shard of a {base_shard_count}-way "
            "federation; it has no reserved id block"
        )
    lo = ADDED_ID_BASE + idx * ADDED_ID_SPAN
    return lo, lo + ADDED_ID_SPAN


@dataclass
class OwnershipMap:
    """A point-in-time read of the ownership log, ready to route."""

    epoch: int = 0
    base_shard_count: int = 1
    shard_count: int = 1
    # job -> shard, from committed migrations (latest commit wins)
    assignments: dict[int, int] = field(default_factory=dict)
    # mig uid -> intent record, for migrations not yet done/aborted
    intents: dict[str, dict] = field(default_factory=dict)
    # mig uid -> True once committed (subset of intents until done)
    committed: set[str] = field(default_factory=set)
    retired: set[str] = field(default_factory=set)   # done or aborted
    verdicts: list[dict] = field(default_factory=list)
    shard_adds: list[dict] = field(default_factory=list)

    def shard_for_job(self, job_id: int) -> int:
        job_id = int(job_id)
        owner = self.assignments.get(job_id)
        if owner is not None:
            return owner
        if job_id > ADDED_ID_BASE:
            shard = (
                self.base_shard_count
                + (job_id - 1 - ADDED_ID_BASE) // ADDED_ID_SPAN
            )
            if shard < self.shard_count:
                return shard
        return (job_id - 1) % max(self.base_shard_count, 1)

    def in_flight(self) -> list[dict]:
        """Live migrations with their protocol phase, newest first."""
        out = []
        for mig, rec in self.intents.items():
            phase = "finalizing" if mig in self.committed else "exporting"
            out.append({**rec, "phase": phase})
        out.sort(key=lambda r: -r.get("epoch", 0))
        return out

    def migration_of(self, mig: str) -> dict | None:
        rec = self.intents.get(mig)
        if rec is not None:
            return rec
        return None

    def owned_counts(self, jobs_by_shard: dict[int, list[int]] | None = None
                     ) -> dict[int, int]:
        """Per-shard count of explicitly reassigned jobs (the map's own
        contribution; modulo-owned jobs are counted by the shards)."""
        counts: dict[int, int] = {}
        for shard in self.assignments.values():
            counts[shard] = counts.get(shard, 0) + 1
        return counts


class OwnershipStore:
    """Reader/writer for the federation root's ownership log."""

    def __init__(self, root: Path):
        self.root = Path(root)
        self.path = self.root / OWNERSHIP_FILE
        self.lock_path = self.root / LOCK_FILE

    # --- plumbing --------------------------------------------------------
    @contextmanager
    def _locked(self):
        fd = os.open(self.lock_path, os.O_CREAT | os.O_RDWR, 0o600)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            os.close(fd)

    def _records(self) -> list[dict]:
        try:
            raw = self.path.read_bytes()
        except FileNotFoundError:
            return []
        records = []
        for line in raw.split(b"\n"):
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                # torn tail from a killed appender: the record never
                # happened. Anything after it is unreachable by
                # construction (appends are serialized by the flock).
                break
        return records

    def _append(self, record: dict) -> dict:
        """Append one record (caller holds the lock), fsynced."""
        record = dict(record)
        record["epoch"] = self.current_epoch() + 1
        record.setdefault("at", clock.now())
        with open(self.path, "ab") as f:
            f.write(json.dumps(record, sort_keys=True).encode() + b"\n")
            f.flush()
            os.fsync(f.fileno())
        return record

    def current_epoch(self) -> int:
        records = self._records()
        return records[-1]["epoch"] if records else 0

    # --- reads -----------------------------------------------------------
    def load(self) -> OwnershipMap:
        from hyperqueue_tpu.utils import serverdir

        fed = serverdir.load_federation(self.root)
        base = int(fed["base_shard_count"]) if fed else 1
        count = int(fed["shard_count"]) if fed else 1
        m = OwnershipMap(base_shard_count=base, shard_count=count)
        for rec in self._records():
            m.epoch = rec["epoch"]
            kind = rec.get("kind")
            if kind == "migration-intent":
                m.intents[rec["mig"]] = rec
            elif kind == "migration-commit":
                rec_i = m.intents.get(rec["mig"])
                if rec_i is not None:
                    m.committed.add(rec["mig"])
                    m.assignments[int(rec_i["job"])] = int(rec_i["to"])
            elif kind in ("migration-done", "migration-abort"):
                m.intents.pop(rec["mig"], None)
                m.committed.discard(rec["mig"])
                m.retired.add(rec["mig"])
            elif kind == "shard-add":
                m.shard_adds.append(rec)
                m.shard_count = max(m.shard_count, int(rec["shard_count"]))
            elif kind == "rebalance":
                m.verdicts.append(rec)
        return m

    # --- migration protocol ----------------------------------------------
    def begin_migration(self, job_id: int, from_shard: int, to_shard: int,
                        mig: str) -> dict:
        """Claim a migration. Idempotent for the SAME mig uid (a crashed
        driver re-claims its own record); a different live migration of
        the same job raises :class:`MigrationClaimed`."""
        with self._locked():
            m = self.load()
            existing = m.intents.get(mig)
            if existing is not None:
                return existing
            if mig in m.retired:
                raise OwnershipError(f"migration {mig} is already retired")
            for other in m.intents.values():
                if int(other["job"]) == int(job_id):
                    raise MigrationClaimed(
                        f"job {job_id} is already migrating under "
                        f"{other['mig']} ({other['from']} -> {other['to']})"
                    )
            owner = m.shard_for_job(job_id)
            if owner != int(from_shard):
                raise OwnershipError(
                    f"job {job_id} is owned by shard {owner}, "
                    f"not {from_shard}"
                )
            return self._append({
                "kind": "migration-intent", "mig": mig,
                "job": int(job_id), "from": int(from_shard),
                "to": int(to_shard),
            })

    def commit_migration(self, mig: str) -> dict | None:
        """The ownership linearization point. Idempotent."""
        with self._locked():
            m = self.load()
            if mig in m.committed or mig in m.retired:
                return None
            if mig not in m.intents:
                raise OwnershipError(f"migration {mig} has no intent")
            return self._append({"kind": "migration-commit", "mig": mig})

    def finish_migration(self, mig: str) -> dict | None:
        with self._locked():
            m = self.load()
            if mig in m.retired:
                return None
            if mig not in m.committed:
                raise OwnershipError(
                    f"migration {mig} is not committed; abort it instead"
                )
            return self._append({"kind": "migration-done", "mig": mig})

    def abort_migration(self, mig: str, reason: str = "") -> dict | None:
        with self._locked():
            m = self.load()
            if mig in m.retired:
                return None
            if mig in m.committed:
                raise OwnershipError(
                    f"migration {mig} is committed; it can only finish"
                )
            if mig not in m.intents:
                return None
            return self._append({
                "kind": "migration-abort", "mig": mig, "reason": reason,
            })

    # --- elasticity ------------------------------------------------------
    def record_shard_add(self, shard_id: int, shard_count: int) -> dict | None:
        """Record an online shard addition. Idempotent per shard id."""
        with self._locked():
            m = self.load()
            for rec in m.shard_adds:
                if int(rec["shard"]) == int(shard_id):
                    return None
            lo, _hi = added_shard_block(shard_id, m.base_shard_count)
            return self._append({
                "kind": "shard-add", "shard": int(shard_id),
                "shard_count": int(shard_count), "id_base": lo,
            })

    def record_verdict(self, verdict: dict) -> dict:
        with self._locked():
            return self._append({"kind": "rebalance", **verdict})
