"""Deterministic fault injection (the chaos harness).

A FaultPlan is a seeded list of rules loaded from the HQ_FAULT_PLAN
environment variable (inline JSON, or `@/path/to/plan.json`). Each process
— server, worker, client — loads its own plan from its own environment, so
a test can fault exactly one side of a connection. Rules fire
deterministically: `at` fires on the Nth matching call only, `times` caps
total fires, `prob` draws from a per-rule RNG seeded by (plan seed, rule
index) — the same plan against the same message sequence always injects
the same faults.

Rule schema (all keys except site/action optional)::

    {"site": "worker.send",            # injection point, see below
     "op": "task_finished",            # match only this message op
     "event": "task-finished",         # match only this event kind (server.event)
     "action": "drop",                 # drop | dup | delay | kill | raise | hang
     "at": 3,                          # fire on the 3rd match only
     "at_t": 42.5,                     # gate: match only at/after this clock time
     "times": 2,                       # fire at most twice
     "prob": 0.25,                     # else fire per-match with this probability
     "delay_ms": 50,                   # for action=delay
     "hang_s": 30}                     # for action=hang

Schedule-driven mode (ISSUE 14): ``at``/``at_t`` triggers make a plan a
deterministic SCHEDULE rather than a sieve — the same plan object fires
the same faults regardless of what else raced through the site counters.
``at_t`` reads :mod:`hyperqueue_tpu.utils.clock`, so under the simulator's
virtual clock a rule pinned to t=600 fires at 600 virtual seconds even
when the whole run takes milliseconds of wall time; ``at`` counts matches
only once the ``at_t`` gate has opened, so "the 3rd journal event after
t=42" composes the two.  Prefer these over ``prob`` rules whenever the
run must be seed-reproducible: a ``prob`` draw consumes the per-rule RNG
in ARRIVAL order, so two runs that interleave messages differently
diverge, while an occurrence/time schedule does not.

In-process harnesses (the simulator, tests) install plans directly with
:func:`install_plan` / :func:`clear_plan` instead of the environment
variable, and may replace the ``kill`` action's process-SIGKILL with
:func:`set_kill_handler` (the simulator maps "kill" onto dropping the
in-process server's state and restoring from the journal).

Sites threaded through the control plane:

- ``worker.send`` / ``worker.recv`` — the worker's uplink messages (before
  batching) and downlink messages;
- ``server.send`` / ``server.recv`` — the server's worker-plane messages
  (recv is per logical message, after batch unpacking);
- ``solve`` — the per-tick scheduler solve (actions raise/hang, guarded by
  the solver watchdog, scheduler/watchdog.py);
- ``server.event`` — Server.emit_event, AFTER the journal write+flush (so
  ``kill`` at event K proves exactly what the flush policy persisted);
- ``server.compact`` — the journal compaction phases (match on ``event``:
  ``mid-snapshot-write`` / ``pre-rename`` / ``post-rename`` / ``mid-gc`` /
  ``pre-swap`` / ``post-swap``), so kill -9 can land inside every window
  of the snapshot+GC crash matrix (docs/fault_tolerance.md);
- ``autoalloc.submit`` — one queue-manager submit attempt (raise = the
  submit fails, kill = server death mid-submit);
- ``autoalloc.spawn`` — the local allocation handler's worker spawn,
  consulted via :func:`decide` with caller-defined action semantics
  (autoalloc/handlers.py LocalHandler: ``drop`` = allocation stuck queued,
  ``hang`` = allocation runs but the worker never registers,
  ``raise`` = the worker boots, registers, then dies).

Faults are injected at the MESSAGE level, not the raw frame level: the
encrypted transport seals frames with counter nonces (transport/auth.py),
so dropping a sealed frame would desynchronize the stream rather than
model a lost message. Dropping/duplicating the message before sealing (or
after opening) exercises the same recovery paths without breaking the
cipher.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import random
import signal
import threading
import time

from hyperqueue_tpu.utils import clock

logger = logging.getLogger("hq.chaos")


class ChaosInjectedError(RuntimeError):
    """Raised by an action="raise" rule (e.g. a poisoned solve)."""


class _Rule:
    __slots__ = (
        "site", "op", "event", "shard", "action", "prob", "at", "at_t",
        "times", "delay_ms", "hang_s", "_matches", "_fired", "_rng",
    )

    def __init__(self, spec: dict, index: int, seed: int):
        self.site = spec["site"]
        self.op = spec.get("op")
        self.event = spec.get("event")
        # scope to one federation shard (ISSUE 17): sites fired from a
        # shard server pass shard=<id>, the migration driver passes
        # shard=-1 ("the coordinator"); rules without the key match all
        self.shard = spec.get("shard")
        self.action = spec["action"]
        self.prob = spec.get("prob")
        self.at = spec.get("at")
        # time gate (wall clock under the active utils/clock provider —
        # virtual time in the simulator): the rule matches nothing before
        # this instant, and `at` counts occurrences only after it
        self.at_t = spec.get("at_t")
        self.times = spec.get("times")
        self.delay_ms = float(spec.get("delay_ms", 25.0))
        self.hang_s = float(spec.get("hang_s", 30.0))
        self._matches = 0
        self._fired = 0
        self._rng = random.Random(f"{seed}:{index}")

    def check(self, site: str, op, event, shard=None) -> bool:
        if site != self.site:
            return False
        if self.op is not None and op != self.op:
            return False
        if self.event is not None and event != self.event:
            return False
        if self.shard is not None and shard != self.shard:
            return False
        if self.at_t is not None and clock.now() < self.at_t:
            return False
        self._matches += 1
        if self.times is not None and self._fired >= self.times:
            return False
        if self.at is not None and self._matches != self.at:
            return False
        if self.prob is not None and self._rng.random() >= self.prob:
            return False
        self._fired += 1
        return True


class FaultPlan:
    def __init__(self, spec: dict):
        self.seed = int(spec.get("seed", 0))
        self.rules = [
            _Rule(r, i, self.seed) for i, r in enumerate(spec.get("rules", ()))
        ]
        # counters are bumped from the event loop AND the solve thread
        self._lock = threading.Lock()

    def match(self, site: str, op=None, event=None, shard=None) -> _Rule | None:
        with self._lock:
            for rule in self.rules:
                if rule.check(site, op, event, shard):
                    logger.warning(
                        "chaos: %s at site=%s op=%s event=%s shard=%s",
                        rule.action, site, op, event, shard,
                    )
                    return rule
        return None


_PLAN: FaultPlan | None = None
# cheap guard for hot paths: `if chaos.ACTIVE:` costs one global load when
# no plan is configured (the overwhelmingly common case)
ACTIVE = False


def _load() -> None:
    global _PLAN, ACTIVE
    raw = os.environ.get("HQ_FAULT_PLAN")
    if not raw:
        return
    if raw.startswith("@"):
        with open(raw[1:]) as f:
            raw = f.read()
    _PLAN = FaultPlan(json.loads(raw))
    ACTIVE = True
    logger.warning(
        "chaos harness active: %d rule(s), seed %d",
        len(_PLAN.rules), _PLAN.seed,
    )


_load()


def install_plan(plan: "FaultPlan | dict | None") -> None:
    """Install a plan programmatically (simulator / in-process tests).

    Replaces whatever HQ_FAULT_PLAN loaded at import.  Passing a dict
    builds a fresh FaultPlan (fresh rule counters); passing None is
    equivalent to :func:`clear_plan`."""
    global _PLAN, ACTIVE
    if isinstance(plan, dict):
        plan = FaultPlan(plan)
    _PLAN = plan
    ACTIVE = plan is not None


def clear_plan() -> None:
    """Remove the active plan (and with it all rule state)."""
    install_plan(None)


def _kill_self() -> None:
    logging.shutdown()
    os.kill(os.getpid(), signal.SIGKILL)


# action="kill" handler: SIGKILL of the process by default.  The simulator
# replaces it with an in-process equivalent (drop the server's in-memory
# state, lose the unflushed journal tail, restore from the journal) so
# kill-at-site rules exercise the same crash choreography without taking
# the test process down.  The handler must not return normally: a real
# kill -9 never does, and code after the injection point must not run.
_KILL_HANDLER = _kill_self


def set_kill_handler(handler) -> None:
    """Replace the action="kill" behavior; None restores SIGKILL-self.
    The handler must unwind the caller (raise) or end the process."""
    global _KILL_HANDLER
    _KILL_HANDLER = handler if handler is not None else _kill_self


# context of the most recent fire() that reached the kill handler: a
# multi-server harness (the federated simulator) installs ONE global kill
# handler but must know WHICH server (or "the coordinator") hit the rule.
# Set just before the handler runs; the handler reads it synchronously.
_LAST_CTX = None


def last_ctx():
    """Context object passed to the fire() that last triggered a kill."""
    return _LAST_CTX


def fire(site: str, op=None, event=None, shard=None, ctx=None) -> None:
    """Synchronous injection point (solve, server.event).

    Applies kill/raise/hang/delay inline (delay and hang are BLOCKING
    sleeps — at server.event that stalls the whole event loop, which is
    the point of injecting them there). drop/dup have no meaning at a
    sync site (there is no message to drop); such rules are rejected
    loudly rather than silently matching and doing nothing. `shard`
    scopes rule matching; `ctx` is recorded for the kill handler (see
    :func:`last_ctx`)."""
    global _LAST_CTX
    if _PLAN is None:
        return
    rule = _PLAN.match(site, op=op, event=event, shard=shard)
    if rule is None:
        return
    if rule.action == "kill":
        _LAST_CTX = ctx
        _KILL_HANDLER()
    if rule.action == "raise":
        raise ChaosInjectedError(f"injected failure at {site}")
    if rule.action == "hang":
        time.sleep(rule.hang_s)
    elif rule.action == "delay":
        time.sleep(rule.delay_ms / 1000.0)
    elif rule.action in ("drop", "dup"):
        logger.error(
            "chaos: action %r is not applicable at sync site %s; ignored",
            rule.action, site,
        )


def decide(site: str, op=None, event=None) -> str | None:
    """Matching injection point whose ACTION the caller interprets.

    For sites where drop/dup/hang model domain behavior rather than a
    message-plane fault (e.g. the local allocation handler's spawn step).
    kill is still applied inline — "die here" means the same everywhere;
    every other action name is returned for the caller to map onto its own
    failure mode."""
    if _PLAN is None:
        return None
    rule = _PLAN.match(site, op=op, event=event)
    if rule is None:
        return None
    if rule.action == "kill":
        _KILL_HANDLER()
    return rule.action


async def on_message(site: str, op=None) -> str | None:
    """Async injection point for message-plane sites.

    Returns "drop" or "dup" for the caller to apply; applies delay (async
    sleep) and kill inline; action=raise raises ChaosInjectedError into
    the connection loop (modeling a poisoned/undecodable message)."""
    if _PLAN is None:
        return None
    rule = _PLAN.match(site, op=op)
    if rule is None:
        return None
    if rule.action == "kill":
        _KILL_HANDLER()
    if rule.action == "raise":
        raise ChaosInjectedError(f"injected failure at {site}")
    if rule.action == "delay":
        await asyncio.sleep(rule.delay_ms / 1000.0)
        return None
    if rule.action == "hang":
        # a hung peer = the message (and everything after it on this
        # plane) stalls for hang_s; async so the rest of the process lives
        await asyncio.sleep(rule.hang_s)
        return None
    if rule.action in ("drop", "dup"):
        return rule.action
    return None
