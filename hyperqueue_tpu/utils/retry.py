"""Jittered exponential backoff, shared by every reconnect/retry loop.

One implementation (worker reconnect, client connect/request retry) so the
jitter range and deadline floor are tuned in one place. Full jitter over
[0.5, 1.0] x delay: enough spread to de-thundering-herd a fleet of workers
reconnecting to one restarted server, while keeping the worst-case wait
predictable (reference AWS architecture blog "exponential backoff and
jitter"; decorrelated jitter buys little at these scales).
"""

from __future__ import annotations

import random


def jittered_backoff(
    delay: float,
    cap: float,
    rng: random.Random,
    remaining: float | None = None,
) -> tuple[float, float]:
    """Returns (seconds_to_sleep_now, next_delay).

    `remaining` clamps the sleep so the last attempt lands at the deadline
    instead of overshooting it (floored at 50 ms so a nearly-expired
    deadline still yields one real wait, not a busy-loop)."""
    sleep_for = delay * rng.uniform(0.5, 1.0)
    if remaining is not None:
        sleep_for = min(sleep_for, max(remaining, 0.05))
    return sleep_for, min(delay * 2, cap)
