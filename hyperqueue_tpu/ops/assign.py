"""Dense scheduling-tick assignment kernel (JAX).

This is the TPU re-host of the reference's per-tick MILP
(crates/tako/src/internal/scheduler/solver.rs:16-461). The reference builds an
integer program with one variable per (worker, rq-batch, variant) and solves it
with HiGHS on the CPU; here the same decision — "how many tasks of each request
class go to each worker this tick" — is computed by a single jit-compiled
program: a `lax.scan` over priority-ordered batches whose body does only dense
(W,) / (W,R) integer vector ops, so the whole tick runs on-device with no
host round-trips and fixed (bucketed) shapes.

Semantics preserved from the reference solver:
  * Strict priority dominance with gap relaxation (solver.rs:240-410): batches
    are scanned highest-priority first; a lower batch sees only the free
    resources left after every higher batch packed maximally, which is exactly
    the reference's blocking-constraint-with-gap outcome for a single tick.
  * Resource variants (request.rs:230): each batch carries up to V variant
    need-vectors tried in user preference order.
  * min_time (request.rs:137): a variant is masked off on workers whose
    remaining lifetime is shorter.
  * Worker objective weights (solver.rs:520-549): the water-fill visits
    workers in an order that penalizes burning scarce resources a batch does
    not request, then lower index first.

Inputs are all integers (fixed-point resource fractions); no floating-point
feasibility drift is possible.

Shapes (padded to buckets by the caller, models/greedy.py):
  free      (W, R) int32   free resource fractions per worker
  nt_free   (W,)   int32   remaining simultaneous-task slots per worker
  lifetime  (W,)   int32   remaining worker lifetime seconds (INF_TIME if none)
  needs     (B, V, R) int32  per-batch per-variant request vector; an all-zero
                             variant row is "variant absent"
  sizes     (B,)   int32   number of ready tasks in the batch (0 = padding row)
  min_time  (B, V) int32   per-variant minimal task duration in seconds
  scarcity  (R,)   float32 precomputed scarcity weight per resource
Output:
  counts    (B, V, W) int32  tasks of batch b, variant v to start on worker w
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

INF_TIME = jnp.int32(2**31 - 1)
# Quantization of the waste score into the integer sort key: key =
# waste_q * W + worker_index, waste_q in [0, _WASTE_Q]. With W <= 16384 the
# key stays well inside int32.
_WASTE_Q = 65536


def _variant_capacity(free, nt_free, need, time_ok):
    """(W,) int32: how many tasks of `need` fit on each worker right now."""
    # floor(free / need) per resource where need > 0, else unlimited
    needed = need > 0
    # avoid div by zero: where need == 0 use 1 and mask with a large number
    denom = jnp.where(needed, need, 1)
    per_res = jnp.where(needed[None, :], free // denom[None, :], jnp.int32(2**30))
    cap = jnp.min(per_res, axis=1)
    cap = jnp.minimum(cap, nt_free)
    cap = jnp.where(time_ok, cap, 0)
    # an absent (all-zero) variant must contribute nothing
    cap = jnp.where(jnp.any(needed), cap, 0)
    return jnp.maximum(cap, 0)


def _water_fill(cap, remaining, order_key):
    """Assign up to `remaining` tasks across workers, preferring low order_key.

    Returns (assign (W,) int32, assigned_total int32). Pure vector math: sort
    workers by key, cumulative-sum capacities, clip, inverse-permute.
    """
    order = jnp.argsort(order_key)  # stable; ascending
    cap_sorted = cap[order]
    cum = jnp.cumsum(cap_sorted)
    take_sorted = jnp.clip(remaining - (cum - cap_sorted), 0, cap_sorted)
    inv = jnp.argsort(order)
    assign = take_sorted[inv]
    return assign, jnp.sum(take_sorted)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def greedy_cut_scan(free, nt_free, lifetime, needs, sizes, min_time, scarcity):
    """Scan priority-ordered batches, water-filling each over the workers.

    See module docstring for shapes/semantics. Returns (counts, free_after,
    nt_free_after).
    """
    n_variants = needs.shape[1]

    def batch_body(carry, batch):
        free, nt_free = carry
        b_needs, b_size, b_min_time = batch
        remaining = b_size
        counts_v = []
        for v in range(n_variants):  # V is tiny and static: unrolled
            need = b_needs[v]
            time_ok = b_min_time[v] <= lifetime
            cap = _variant_capacity(free, nt_free, need, time_ok)
            cap = jnp.minimum(cap, remaining)
            # Worker order: burning resources the batch does not request is
            # penalized by their scarcity; ties broken by worker index
            # (reference solver.rs:520-549 objective weights). scarcity is
            # normalized to sum 1 so waste is in [0, 1]; the key is integer to
            # keep the index tiebreak exact.
            n_workers = cap.shape[0]
            unneeded = (free > 0) & (need[None, :] == 0)
            waste = jnp.sum(unneeded * scarcity[None, :], axis=1)
            waste_q = jnp.round(waste * _WASTE_Q).astype(jnp.int32)
            idx = jnp.arange(n_workers, dtype=jnp.int32)
            order_key = jnp.where(
                cap > 0, waste_q * n_workers + idx, jnp.int32(2**31 - 1)
            )
            assign, assigned = _water_fill(cap, remaining, order_key)
            remaining = remaining - assigned
            free = free - assign[:, None] * need[None, :]
            nt_free = nt_free - assign
            counts_v.append(assign)
        return (free, nt_free), jnp.stack(counts_v)

    (free, nt_free), counts = jax.lax.scan(
        batch_body, (free, nt_free), (needs, sizes, min_time)
    )
    return counts, free, nt_free


def scarcity_weights(total_amounts: jnp.ndarray) -> jnp.ndarray:
    """(R,) float32 scarcity per resource, normalized to sum 1.

    Rarer cluster-wide => larger weight. Resources with zero total capacity
    get weight 0 (nobody can waste them). total_amounts: (R,) summed capacity
    across workers.
    """
    total = total_amounts.astype(jnp.float32)
    present = total > 0
    inv = jnp.where(present, jnp.max(total) / jnp.maximum(total, 1.0), 0.0)
    norm = jnp.sum(inv)
    return jnp.where(norm > 0, inv / jnp.maximum(norm, 1e-9), 0.0)
