"""Dense scheduling-tick assignment kernel (JAX).

This is the TPU re-host of the reference's per-tick MILP
(crates/tako/src/internal/scheduler/solver.rs:16-461). The reference builds an
integer program with one variable per (worker, rq-batch, variant) and solves it
with HiGHS on the CPU; here the same decision — "how many tasks of each request
class go to each worker this tick" — is computed by a single jit-compiled
program: a `lax.scan` over priority-ordered batches whose body does only dense
(W,) / (W,R) integer vector ops, so the whole tick runs on-device with no
host round-trips and fixed (bucketed) shapes.

Semantics preserved from the reference solver:
  * Strict priority dominance with gap relaxation (solver.rs:240-410): batches
    are scanned highest-priority first; a lower batch sees only the free
    resources left after every higher batch packed maximally, which is exactly
    the reference's blocking-constraint-with-gap outcome for a single tick.
  * Resource variants (request.rs:230): each batch carries up to V variant
    need-vectors tried in user preference order.
  * min_time (request.rs:137): a variant is masked off on workers whose
    remaining lifetime is shorter.
  * Worker objective weights (solver.rs:520-549): the water-fill visits
    workers in an order that penalizes burning scarce resources a batch does
    not request, then lower index first.

Inputs are all integers (fixed-point resource fractions); no floating-point
feasibility drift is possible.

Shapes (padded to buckets by the caller, models/greedy.py):
  free      (W, R) int32   free resource fractions per worker
  nt_free   (W,)   int32   remaining simultaneous-task slots per worker
  lifetime  (W,)   int32   remaining worker lifetime seconds (INF_TIME if none)
  needs     (B, V, R) int32  per-batch per-variant request vector; an all-zero
                             variant row is "variant absent"
  sizes     (B,)   int32   number of ready tasks in the batch (0 = padding row)
  min_time  (B, V) int32   per-variant minimal task duration in seconds
  scarcity  (R,)   float32 precomputed scarcity weight per resource
Output:
  counts    (B, V, W) int32  tasks of batch b, variant v to start on worker w
"""

from __future__ import annotations

import functools

# INF_TIME is re-exported here for kernel callers/tests
from hyperqueue_tpu.utils.constants import INF_TIME  # noqa: F401

# jax is imported LAZILY: the host-side functions in this module
# (host_visit_classes, scarcity_weights, greedy_cut_scan_numpy) are pure
# numpy and serve the CPU production path, where pulling in jax costs
# several seconds of server/worker startup per process (measured ~4 s
# cold).  _load_jax() installs jax/jnp into the module globals the first
# time a kernel entry point actually runs.
jax = None
jnp = None


def _load_jax() -> None:
    global jax, jnp
    if jax is None:
        import jax as _jax
        import jax.numpy as _jnp

        jax = _jax
        jnp = _jnp
# Quantization of the waste score into the integer sort key: key =
# waste_q * W + worker_index, waste_q in [0, _WASTE_Q]. With W <= 16384 the
# key stays well inside int32.
_WASTE_Q = 65536

# Policy affinity weights are clamped to [0, _AFF_MAX] before quantization;
# the visit-class key combines (-affinity, waste) lexicographically, so the
# affinity term needs a multiplier strictly above the waste range.
_AFF_MAX = 256.0
_AFF_STRIDE = _WASTE_Q * 2  # > max waste_q (waste <= 1 since scarcity sums to 1)


MAX_KERNEL_AMOUNT = 2**23  # all amounts must be below this (float32-exact)


def _variant_capacity(free, nt_free, need, time_ok, total=None, all_r=None):
    """(W,) int32: how many tasks of `need` fit on each worker right now.

    TPUs have no hardware integer division; XLA expands `//` into a long
    scalar sequence that dominated the scan. Instead: float32 division plus an
    exact integer fixup. Precondition (enforced by the range compression in
    scheduler/tick.py / models/greedy.py): free and need < 2^23, so both are
    exactly representable in float32 and the float quotient is within 1 of
    the true floor — two int32 multiply-compare corrections make it exact.

    all_r (R,) int32 0/1 marks ALL-policy resources (request.rs:14-21 All):
    the task takes the worker's ENTIRE pool of that resource, so it fits only
    where the pool is untouched (free == total, reference solver.rs:120-124
    amount_or_none_if_all) — at most one such task per worker per tick.
    """
    needed = need > 0
    denom = jnp.where(needed, need, 1)
    q = jnp.floor(
        free.astype(jnp.float32) * (1.0 / denom.astype(jnp.float32))[None, :]
    ).astype(jnp.int32)
    # exact floor-division fixup (all int32 multiplies)
    too_big = q * denom[None, :] > free
    q = q - too_big.astype(jnp.int32)
    too_small = (q + 1) * denom[None, :] <= free
    q = q + too_small.astype(jnp.int32)
    per_res = jnp.where(needed[None, :], q, jnp.int32(2**30))
    any_req = jnp.any(needed)
    if all_r is not None:
        is_all = all_r > 0
        all_fit = ((free == total) & (total > 0)).astype(jnp.int32)
        per_res = jnp.where(
            is_all[None, :], all_fit, per_res
        )
        any_req = any_req | jnp.any(is_all)
    cap = jnp.min(per_res, axis=1)
    cap = jnp.minimum(cap, nt_free)
    cap = jnp.where(time_ok, cap, 0)
    # an absent (all-zero) variant must contribute nothing
    cap = jnp.where(any_req, cap, 0)
    return jnp.maximum(cap, 0)


def _water_fill_classed(
    cap, remaining, class_onehot, per_class_total=None, same_class_before=0
):
    """Water-fill in (waste-class asc, worker-index asc) visit order without
    any sort or permutation gather.

    class_onehot: (W, C) int32 0/1, class 0 visited first; within a class,
    workers are visited in index order. The prefix (capacity absorbed before
    worker w) = total capacity of strictly-lower classes + exclusive
    index-order cumsum within w's own class — all elementwise ops + cumsums,
    which TPUs execute in microseconds where a 1024-element permutation
    gather costs ~140us.

    The multi-chip kernel runs this SAME function on each worker shard
    (parallel/solve.py): `per_class_total` (C,) is then the cluster-wide
    per-class capacity (local sums by default — the single-chip case) and
    `same_class_before` (C,) the same-class capacity on lower-index devices
    (0 single-chip), which together shift each local prefix to its global
    position. Returns (assign (W,), assigned_total = min(remaining, total
    capacity) — the global total even when workers are sharded).
    """
    _load_jax()
    cap_c = cap[:, None] * class_onehot  # (W, C)
    per_class = jnp.sum(cap_c, axis=0)  # (C,)
    if per_class_total is None:
        per_class_total = per_class
    class_before = (
        jnp.cumsum(per_class_total) - per_class_total
    )  # exclusive (C,)
    within_excl = jnp.cumsum(cap_c, axis=0) - cap_c  # (W, C)
    prefix = jnp.sum(
        (within_excl + (class_before + same_class_before)[None, :])
        * class_onehot,
        axis=1,
    )
    assign = jnp.clip(remaining - prefix, 0, cap)
    # water-fill identity: total assigned = min(remaining, total capacity)
    # (cap >= 0 everywhere, prefix is the exact global exclusive prefix) —
    # no reduction over `assign` needed, which on a sharded axis would cost
    # a second collective
    return assign, jnp.minimum(remaining, jnp.sum(per_class_total))


# fixed class-axis width for the gather-free water-fill; distinct waste
# levels per mask are bounded by distinct worker resource patterns and are
# clamped here (overflowing classes merge into the last one, which only
# relaxes the preference order among the most-wasteful workers)
N_VISIT_CLASSES = 16


def host_visit_classes(free0, needs, scarcity, all_mask=None, affinity=None):
    """Precompute worker visit classes per distinct request mask (numpy).

    The preference order (avoid burning scarce resources a request does not
    need, then lower worker index — reference solver.rs:520-549 objective) is
    a per-tick static choice depending only on (a) which resources each
    request does NOT use and (b) which resources each worker has. Distinct
    "unused resource" masks per tick are few (M << B*V). Instead of materializing
    permutations (arbitrary-permutation gathers cost ~140us per scan step on
    TPU), each worker gets a visit CLASS = dense rank of its waste score; the
    kernel water-fills class-by-class with cumsums only.

    affinity (B, W) float, optional: per-(batch, worker) policy weight (the
    heterogeneity matrix `S` sliced per batch row). The visit key becomes the
    lexicographic pair (-affinity, waste): higher-throughput workers are
    water-filled first, waste breaks ties. Deduplication then keys on (mask,
    affinity row) so two batches with identical request shapes but different
    weight rows get distinct classes. With affinity=None the behavior is
    bit-identical to the unweighted kernel.

    Returns (class_m (M, W) int32 in [0, N_VISIT_CLASSES), order_ids (B, V)
    int32). Only ~M*W ints cross the host->device boundary per tick.
    """
    import numpy as np

    n_b, n_v, _n_r = needs.shape
    has = np.asarray(free0) > 0  # (W, R)
    masks = np.asarray(needs) == 0  # (B, V, R): resources NOT requested
    if all_mask is not None:
        # an ALL-policy entry requests the resource (amount is the pool)
        masks = masks & ~(np.asarray(all_mask) > 0)
    flat = masks.reshape(n_b * n_v, -1)
    if affinity is None:
        uniq, inverse = np.unique(flat, axis=0, return_inverse=True)
        aff_u = None
    else:
        aff = np.clip(np.asarray(affinity, dtype=np.float64), 0.0, _AFF_MAX)
        aff_q = np.round(aff * _WASTE_Q).astype(np.int64)  # (B, W)
        aff_rep = np.repeat(aff_q, n_v, axis=0)  # (B*V, W)
        combined = np.concatenate([flat.astype(np.int64), aff_rep], axis=1)
        _u, index, inverse = np.unique(
            combined, axis=0, return_index=True, return_inverse=True
        )
        uniq = flat[index]
        aff_u = aff_rep[index]  # (M, W)
    weighted = has * np.asarray(scarcity)[None, :]  # (W, R)
    waste = np.einsum("mr,wr->mw", uniq.astype(np.float32), weighted)
    waste_q = np.round(waste * _WASTE_Q).astype(np.int64)
    key = waste_q if aff_u is None else waste_q - aff_u * np.int64(_AFF_STRIDE)
    class_m = np.empty_like(key, dtype=np.int32)
    for m in range(key.shape[0]):
        levels = np.unique(key[m])  # sorted ascending
        class_m[m] = np.searchsorted(levels, key[m]).astype(np.int32)
    np.clip(class_m, 0, N_VISIT_CLASSES - 1, out=class_m)
    order_ids = inverse.reshape(n_b, n_v).astype(np.int32)
    return class_m, order_ids


def expand_onehots(class_m, order_ids):
    """Per-batch visit-class one-hots (B, V, W, C) int32 — built with one
    broadcasted compare outside the scan. The optimization barrier stops
    XLA from fusing this into the scan body (it would re-gather
    class_m[order_ids[i]] every step — a dynamic row gather costing
    ~140us/step; measured 84ms vs 0.1ms for the whole tick)."""
    _load_jax()
    class_ids = class_m[order_ids]  # (B, V, W)
    onehots = (
        class_ids[..., None]
        == jnp.arange(N_VISIT_CLASSES, dtype=jnp.int32)
    ).astype(jnp.int32)
    return jax.lax.optimization_barrier(onehots)


def _gang_select_local(elig, group_onehot, n):
    """Pick the gang's worker set from one device's full worker view.

    elig (W,) int32 0/1, group_onehot (W, G) int32, n scalar gang size.
    Chooses the FIRST group with >= n eligible workers (else the group with
    the most, for holdback), then the n lowest-index eligible members.
    Returns (take (W,) int32 0/1, any_feasible bool). The sharded kernel
    plugs in a collective variant (parallel/solve.py) with the same
    contract.
    """
    per_group = jnp.sum(elig[:, None] * group_onehot, axis=0)  # (G,)
    feasible = per_group >= n
    any_feas = jnp.any(feasible)
    chosen = jnp.where(
        any_feas, jnp.argmax(feasible), jnp.argmax(per_group)
    )
    col = jnp.sum(
        group_onehot
        * (jnp.arange(group_onehot.shape[1], dtype=jnp.int32)
           == chosen)[None, :],
        axis=1,
    )
    sel = elig * col
    prefix = jnp.cumsum(sel) - sel
    take = sel * (prefix < n).astype(jnp.int32)
    return take, any_feas


def scan_batches(
    free, nt_free, lifetime, needs, sizes, min_time, onehots, water_fill,
    total=None, all_mask=None,
    gang_nodes=None, gang_ok=None, group_onehot=None, gang_select=None,
    policy_mask=None,
):
    """Scan priority-ordered batches, water-filling each over the workers.

    The ONE scan body shared by the single-chip and multi-chip kernels —
    parity between them is structural, not test-maintained: the sharded path
    (parallel/solve.py) differs only in the `water_fill` it plugs in (its
    prefix spans devices via all_gather).

    water_fill(cap, remaining, class_onehot) -> (assign (W,), assigned_total);
    `assigned_total` must be the GLOBAL total when workers are sharded.
    total (W, R) and all_mask (B, V, R) enable ALL-policy requests: an
    assigned ALL task drains the worker's whole pool of the marked resources
    (reference solver.rs:120-124). Returns (counts, free_after,
    nt_free_after).

    Gang rows (all-or-nothing column groups): gang_nodes (B,) int32 marks
    batch rows that are one multi-node gang each (0 = ordinary row);
    gang_ok (W,) int32 0/1 is host idleness (a gang member must be fully
    idle — prefilled backlog does not show in `free`, so free==total is NOT
    sufficient); group_onehot (W, G) int32 maps workers to worker groups.
    The scan carries a gang-availability vector that starts at gang_ok and
    is zeroed by ANY in-scan assignment, so a gang only sees workers still
    untouched this solve. A feasible gang row emits n co-scheduled counts
    in variant 0; feasible or not, the selected workers are HELD (free/nt
    zeroed) for the rest of the scan — the in-solve equivalent of the host
    `mn_reserved` reservation drain, so lower-priority work cannot steal
    members while a gang accumulates.

    policy_mask (B, W) int32 0/1, optional: zero marks workers a batch's
    policy weight row excludes (affinity 0 = hard incompatibility per the
    Gavel throughput-matrix semantics). A masked worker contributes no
    capacity to the batch and is ineligible as a gang member. Callers pass
    it only when at least one zero exists; the all-ones mask is the None
    path.
    """
    _load_jax()
    n_variants = needs.shape[1]
    has_all = all_mask is not None
    has_gang = gang_nodes is not None
    has_pmask = policy_mask is not None
    if has_gang and gang_select is None:
        gang_select = _gang_select_local

    def batch_body(carry, batch):
        if has_gang:
            free, nt_free, gang_avail = carry
        else:
            free, nt_free = carry
            gang_avail = None
        batch = list(batch)
        b_needs, b_size, b_min_time, b_onehot = batch[:4]
        rest = batch[4:]
        b_all = rest.pop(0) if has_all else None
        b_gang = rest.pop(0) if has_gang else None
        b_pmask = rest.pop(0) if has_pmask else None
        remaining = b_size
        counts_v = []
        emit = None
        if has_gang:
            is_gang = (b_gang > 0).astype(jnp.int32)
            time_ok0 = (b_min_time[0] <= lifetime).astype(jnp.int32)
            elig = (
                gang_avail * time_ok0
                * (nt_free >= 1).astype(jnp.int32)
            )
            if has_pmask:
                elig = elig * b_pmask
            take, any_feas = gang_select(elig, group_onehot, b_gang)
            take = take * is_gang
            emit = take * any_feas.astype(jnp.int32)
            free = free * (1 - take)[:, None]
            nt_free = nt_free * (1 - take)
            gang_avail = gang_avail * (1 - take)
            # a gang row is ONLY its all-or-nothing emit: the ordinary
            # water-fill below must not also spend its size on stragglers
            remaining = remaining * (1 - is_gang)
        for v in range(n_variants):  # V is tiny and static: unrolled
            need = b_needs[v]
            time_ok = b_min_time[v] <= lifetime
            all_r = b_all[v] if has_all else None
            cap = _variant_capacity(
                free, nt_free, need, time_ok, total=total, all_r=all_r
            )
            cap = jnp.minimum(cap, remaining)
            if has_pmask:
                cap = cap * b_pmask
            assign, assigned = water_fill(cap, remaining, b_onehot[v])
            remaining = remaining - assigned
            free = free - assign[:, None] * need[None, :]
            if has_all:
                # an ALL assignment (assign is 0/1 there: cap <= 1) empties
                # the worker's pool of the marked resources
                free = free * (1 - assign[:, None] * all_r[None, :])
            nt_free = nt_free - assign
            if has_gang:
                gang_avail = gang_avail * (assign == 0).astype(jnp.int32)
            counts_v.append(assign)
        if has_gang:
            counts_v[0] = counts_v[0] + emit
            return (free, nt_free, gang_avail), jnp.stack(counts_v)
        return (free, nt_free), jnp.stack(counts_v)

    xs = (needs, sizes, min_time, onehots)
    if has_all:
        xs = xs + (all_mask,)
    if has_gang:
        xs = xs + (gang_nodes,)
    if has_pmask:
        xs = xs + (policy_mask,)
    if has_gang:
        carry0 = (free, nt_free, gang_ok.astype(jnp.int32))
        (free, nt_free, _), counts = jax.lax.scan(batch_body, carry0, xs)
    else:
        (free, nt_free), counts = jax.lax.scan(
            batch_body, (free, nt_free), xs
        )
    return counts, free, nt_free


def greedy_cut_scan_impl(
    free, nt_free, lifetime, needs, sizes, min_time, class_m, order_ids,
    total=None, all_mask=None,
    gang_nodes=None, gang_ok=None, group_onehot=None, policy_mask=None,
):
    """Single-chip kernel: one-hot expansion + the shared batch scan.

    Un-jitted implementation (jit-wrapped below; also reused by the driver
    entry). class_m (M, W) int32 + order_ids (B, V) int32 come from
    host_visit_classes: per distinct request mask, each worker's visit class
    (0 = visited first). total/all_mask enable ALL-policy requests;
    gang_nodes/gang_ok/group_onehot enable all-or-nothing gang rows (see
    scan_batches). See module docstring for shapes/semantics. Returns
    (counts, free_after, nt_free_after).
    """
    onehots = expand_onehots(class_m, order_ids)
    return scan_batches(
        free, nt_free, lifetime, needs, sizes, min_time, onehots,
        _water_fill_classed, total=total, all_mask=all_mask,
        gang_nodes=gang_nodes, gang_ok=gang_ok, group_onehot=group_onehot,
        policy_mask=policy_mask,
    )


_greedy_cut_scan_jit = None


def greedy_cut_scan(*args, **kwargs):
    """Jitted single-chip kernel (donate_argnums=(0, 1): the free/nt_free
    device buffers are consumed and their storage reused for the outputs).
    The jit wrapper is built on first call so importing this module never
    pulls in jax (see _load_jax)."""
    global _greedy_cut_scan_jit
    if _greedy_cut_scan_jit is None:
        _load_jax()
        _greedy_cut_scan_jit = functools.partial(
            jax.jit, donate_argnums=(0, 1)
        )(greedy_cut_scan_impl)
    return _greedy_cut_scan_jit(*args, **kwargs)


def greedy_cut_scan_numpy(
    free, nt_free, lifetime, needs, sizes, min_time, class_m, order_ids,
    total=None, all_mask=None,
    gang_nodes=None, gang_ok=None, group_onehot=None, policy_mask=None,
):
    """Vectorized numpy implementation of the cut-scan (identical semantics).

    The jitted scan is the TPU path; on CPU the XLA while-loop overhead
    (~70 ms for 512 steps at W=1024) loses to plain numpy (~15 ms), so this
    is the host fallback the model picks when no accelerator is present.
    """
    import numpy as np

    free = np.asarray(free, dtype=np.int64).copy()
    nt_free = np.asarray(nt_free, dtype=np.int64).copy()
    lifetime = np.asarray(lifetime)
    if total is not None:
        total = np.asarray(total, dtype=np.int64)
    n_b, n_v, _n_r = needs.shape
    n_w = free.shape[0]
    counts = np.zeros((n_b, n_v, n_w), dtype=np.int32)
    class_ids = np.asarray(class_m)[np.asarray(order_ids)]  # (B, V, W)
    idx = np.arange(n_w)
    has_gang = gang_nodes is not None
    if has_gang:
        gang_nodes = np.asarray(gang_nodes)
        gang_avail = np.asarray(gang_ok, dtype=bool).copy()
        group_oh = np.asarray(group_onehot, dtype=bool)  # (W, G)
    pmask = (
        np.asarray(policy_mask) > 0 if policy_mask is not None else None
    )  # (B, W) bool

    for b in range(n_b):
        remaining = int(sizes[b])
        if has_gang and gang_nodes[b] > 0:
            # all-or-nothing gang row (see scan_batches): feasible -> emit
            # n co-scheduled counts in variant 0; either way HOLD the
            # selected workers for the rest of the scan
            n = int(gang_nodes[b])
            elig = (
                gang_avail
                & (min_time[b, 0] <= lifetime)
                & (nt_free >= 1)
            )
            if pmask is not None:
                elig = elig & pmask[b]
            per_group = (elig[:, None] & group_oh).sum(axis=0)  # (G,)
            feasible = per_group >= n
            chosen = int(
                np.argmax(feasible) if feasible.any()
                else np.argmax(per_group)
            )
            sel = elig & group_oh[:, chosen]
            prefix = np.cumsum(sel) - sel
            take = sel & (prefix < n)
            if feasible.any():
                counts[b, 0, take] = 1
            free[take] = 0
            nt_free[take] = 0
            gang_avail[take] = False
            continue
        for v in range(n_v):
            if remaining <= 0:
                break
            need = needs[b, v]
            needed = need > 0
            all_r = (
                np.asarray(all_mask[b, v]) > 0 if all_mask is not None
                else np.zeros_like(needed)
            )
            if not needed.any() and not all_r.any():
                continue
            if needed.any():
                per_res = np.min(
                    free[:, needed]
                    // np.asarray(need, dtype=np.int64)[needed],
                    axis=1,
                )
            else:
                per_res = np.full(n_w, 2**30, dtype=np.int64)
            if all_r.any():
                # ALL-policy resources: fits only on a fully idle pool,
                # at most one task per worker (solver.rs:120-124)
                all_fit = (
                    (free[:, all_r] == total[:, all_r])
                    & (total[:, all_r] > 0)
                ).all(axis=1)
                per_res = np.minimum(per_res, all_fit.astype(np.int64))
            cap = np.minimum(per_res, nt_free)
            cap[min_time[b, v] > lifetime] = 0
            np.clip(cap, 0, remaining, out=cap)
            if pmask is not None:
                cap[~pmask[b]] = 0
            if not cap.any():
                continue
            order = np.lexsort((idx, class_ids[b, v]))
            cap_sorted = cap[order]
            cum = np.cumsum(cap_sorted)
            take_sorted = np.clip(remaining - (cum - cap_sorted), 0, cap_sorted)
            assign = np.empty(n_w, dtype=np.int64)
            assign[order] = take_sorted
            assigned = int(take_sorted.sum())
            remaining -= assigned
            free -= assign[:, None] * need[None, :]
            if all_r.any():
                free[:, all_r] *= 1 - assign[:, None]
            nt_free -= assign
            if has_gang:
                gang_avail[assign > 0] = False
            counts[b, v] = assign
    return counts, free, nt_free


def scarcity_weights(total_amounts) -> "np.ndarray":
    """(R,) float32 scarcity per resource, normalized to sum 1 (numpy, host).

    Rarer cluster-wide => larger weight. Resources with zero total capacity
    get weight 0 (nobody can waste them). total_amounts: (R,) summed capacity
    across workers.

    Deliberately numpy, not jnp: this feeds the host-side visit-class
    computation, and a single EAGER jnp op degrades every subsequent compiled
    dispatch on the axon TPU runtime from ~40us to ~80ms (measured) — the
    server must never run eager device ops.
    """
    import numpy as np

    total = np.asarray(total_amounts, dtype=np.float64)
    present = total > 0
    inv = np.where(present, total.max(initial=0.0) / np.maximum(total, 1.0), 0.0)
    norm = inv.sum()
    if norm <= 0:
        return np.zeros_like(total, dtype=np.float32)
    return (inv / norm).astype(np.float32)
