"""Virtual-clock asyncio event loop: the simulator's time machine.

The loop's ``time()`` is a virtual monotonic clock.  Whenever every ready
callback has run and only timers remain, the clock JUMPS to the next timer
deadline instead of sleeping — a ten-minute lease timeout costs the same
wall time as a 10 ms scheduler delay, and a quiet night of heartbeats is
free.  Within one instant, callback ordering is exactly asyncio's FIFO
ready queue, so a run is a deterministic function of the code + the seed
(no kernel scheduling, no socket buffering, no thread interleaving).

Three deviations from a stock ``SelectorEventLoop``:

- ``time()`` returns the virtual clock; timers scheduled with
  ``call_later``/``call_at`` (and everything built on them —
  ``asyncio.sleep``, timeouts, the server's min-delay throttle) run in
  virtual time.
- ``run_in_executor`` executes the function INLINE and returns a finished
  future.  The server offloads journal restore and compaction snapshots to
  an executor; in the simulator those run synchronously on the loop so no
  real thread can interleave with simulated state.
- A fully idle loop (no ready callbacks, no timers, not stopping) is a
  deadlock by construction — nothing can ever wake it, because the
  simulation owns every event source.  It raises :class:`SimDeadlockError`
  instead of blocking forever, with the pending-task inventory in the
  message.

The per-process clock seam (``utils/clock.py``) is bridged by
:class:`SimClock`: ``monotonic()`` is the loop's virtual time and
``time()`` maps it onto a fixed epoch (plus an adjustable skew, the
clock-skew fault's lever), so all ~117 swept call sites across the server
tick with the simulation.
"""

from __future__ import annotations

import asyncio
import heapq
import selectors

# the virtual wall clock starts here: an arbitrary fixed epoch, so journal
# record stamps are identical run-to-run (and obviously fake in dumps)
SIM_EPOCH = 1_600_000_000.0


class SimDeadlockError(RuntimeError):
    """The virtual loop went fully idle with work still pending."""


class SimEventLoop(asyncio.SelectorEventLoop):
    """SelectorEventLoop whose clock jumps to the next timer deadline."""

    def __init__(self) -> None:
        super().__init__(selectors.SelectSelector())
        self._sim_time = 0.0

    def time(self) -> float:
        return self._sim_time

    def _advance_clock(self) -> None:
        # mirror of base _run_once's cancelled-timer cleanup, needed
        # before peeking at the heap head for the true next deadline
        while self._scheduled and self._scheduled[0]._cancelled:
            handle = heapq.heappop(self._scheduled)
            handle._scheduled = False
        if self._scheduled:
            when = self._scheduled[0]._when
            if when > self._sim_time:
                self._sim_time = when
            return
        # nothing ready, nothing scheduled, not stopping: no event source
        # exists that could ever wake this loop again
        pending = [
            t for t in asyncio.all_tasks(self) if not t.done()
        ]
        names = ", ".join(sorted(
            (t.get_coro().__qualname__ if t.get_coro() else repr(t))
            for t in pending
        )[:12])
        raise SimDeadlockError(
            f"virtual clock has nothing to advance to at t={self._sim_time:.6f}"
            f" with {len(pending)} pending task(s): {names or 'none'}"
        )

    def _run_once(self) -> None:
        if not self._ready and not self._stopping:
            self._advance_clock()
        super()._run_once()

    def run_in_executor(self, executor, func, *args):
        fut = self.create_future()
        try:
            fut.set_result(func(*args))
        except BaseException as e:  # noqa: BLE001 - ferried to the caller
            fut.set_exception(e)
        return fut


class SimClock:
    """utils/clock provider backed by a :class:`SimEventLoop`.

    ``monotonic()`` IS the loop's virtual time, so asyncio timers and the
    server's monotonic bookkeeping (heartbeat ages, lease renewals,
    reattach deadlines) can never disagree.  ``skew`` shifts only the wall
    clock — the clock-skew fault: journal stamps and lease records jump
    while monotonic durations stay truthful, exactly what a stepped NTP
    correction does to a real host."""

    __slots__ = ("_loop", "epoch", "skew")

    def __init__(self, loop: SimEventLoop, epoch: float = SIM_EPOCH):
        self._loop = loop
        self.epoch = float(epoch)
        self.skew = 0.0

    def time(self) -> float:
        return self.epoch + self._loop.time() + self.skew

    def monotonic(self) -> float:
        return self._loop.time()
