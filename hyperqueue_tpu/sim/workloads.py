"""Synthetic workload generators for the simulator.

A workload is a list of :class:`SubmitSpec`s — (virtual arrival time, job
description, expected task count) — that the harness submits through the
real client plane at the right virtual instants.  Task run times live in
the shared body (``{"sim": {...}}``, see ``sim/worker.py
task_duration_s``) so a million-task array still ships one body.

Shapes mirror the scenario suite the roadmap asks for:

- :func:`uniform_array` — one big array job, the saturation baseline;
- :func:`bursty_multi_tenant` — N tenants submitting bursts at seeded
  arrival times with mixed priorities and sizes;
- :func:`deep_dag` — layered diamond graphs (the stress-dag shape):
  critical-path-bound completion, exercises dependency propagation;
- :func:`gang_heavy` — a mix of multi-node gangs and single-node filler,
  exercising reservation/drain interplay;
- :func:`straggler_tailed` — wide short tasks with a heavy duration tail,
  the shape retract/rebalance exists for.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field


@dataclass
class SubmitSpec:
    at: float                  # virtual submit time
    job_desc: dict             # wire job description ({"op": "submit"} body)
    n_tasks: int
    expect_failed: int = 0


@dataclass
class Workload:
    name: str
    submits: list[SubmitSpec] = field(default_factory=list)

    @property
    def n_tasks(self) -> int:
        return sum(s.n_tasks for s in self.submits)

    @property
    def expect_failed(self) -> int:
        return sum(s.expect_failed for s in self.submits)

    @property
    def horizon_hint(self) -> float:
        return max((s.at for s in self.submits), default=0.0)


def _array_desc(name: str, n: int, body: dict, cpus: int = 1,
                priority: int = 0) -> dict:
    return {
        "name": name,
        "submit_dir": "/sim",
        "array": {
            "id_range": [0, n],
            "body": body,
            "request": {"variants": [{"entries": [
                {"name": "cpus", "amount": cpus * 10_000},
            ]}]},
            "priority": priority,
        },
    }


def uniform_array(n_tasks: int = 1000, dur_ms: float = 500.0,
                  seed: int = 0) -> Workload:
    body = {"sim": {"dur_range_ms": [dur_ms * 0.5, dur_ms * 1.5],
                    "seed": seed}}
    return Workload("uniform-array", [
        SubmitSpec(at=0.0, job_desc=_array_desc("uniform", n_tasks, body),
                   n_tasks=n_tasks),
    ])


def bursty_multi_tenant(n_tenants: int = 4, bursts_per_tenant: int = 3,
                        tasks_per_burst: int = 200, window: float = 120.0,
                        seed: int = 0,
                        tenant_dur_scales: list | None = None) -> Workload:
    """`tenant_dur_scales` (opt-in, policy A/B) scales tenant i's task
    durations by scales[i % len]: heterogeneous per-tenant runtimes give
    the runtime predictor distinct classes to learn and the fairness fold
    a skewed usage profile. Default None is byte-identical to the
    original shape (the digest-pinned determinism tests)."""
    rng = random.Random(f"bursty:{seed}")
    submits = []
    for tenant in range(n_tenants):
        priority = rng.choice([-1, 0, 0, 1])
        scale = (
            tenant_dur_scales[tenant % len(tenant_dur_scales)]
            if tenant_dur_scales else 1.0
        )
        for burst in range(bursts_per_tenant):
            at = rng.uniform(0.0, window)
            n = max(int(tasks_per_burst * rng.uniform(0.3, 1.7)), 1)
            body = {"sim": {"dur_range_ms": [100 * scale, 2000 * scale],
                            "seed": seed * 1000 + tenant}}
            submits.append(SubmitSpec(
                at=at,
                job_desc=_array_desc(
                    f"tenant{tenant}-burst{burst}", n, body,
                    priority=priority,
                ),
                n_tasks=n,
            ))
    return Workload("bursty-multi-tenant", submits)


def deep_dag(layers: int = 12, width: int = 24, seed: int = 0) -> Workload:
    """Layered diamond DAG (the stress-dag shape): layer k+1 tasks depend
    on two tasks of layer k."""
    rng = random.Random(f"dag:{seed}")
    tasks = []
    tid = 0
    prev_layer: list[int] = []
    for layer in range(layers):
        this_layer = []
        for i in range(width):
            deps = []
            if prev_layer:
                deps = sorted(rng.sample(
                    prev_layer, k=min(2, len(prev_layer))
                ))
            tasks.append({
                "id": tid,
                "deps": deps,
                "body": {"sim": {"dur_range_ms": [50, 400],
                                 "seed": seed}},
                "request": {"variants": [{"entries": [
                    {"name": "cpus", "amount": 10_000},
                ]}]},
            })
            this_layer.append(tid)
            tid += 1
        prev_layer = this_layer
    desc = {"name": "deep-dag", "submit_dir": "/sim", "tasks": tasks}
    return Workload("deep-dag", [
        SubmitSpec(at=0.0, job_desc=desc, n_tasks=len(tasks)),
    ])


def gang_heavy(n_gangs: int = 8, gang_size: int = 4,
               filler_tasks: int = 400, seed: int = 0) -> Workload:
    rng = random.Random(f"gang:{seed}")
    submits = []
    for g in range(n_gangs):
        desc = {
            "name": f"gang{g}",
            "submit_dir": "/sim",
            "tasks": [{
                "id": 0,
                "body": {"sim": {"dur_ms": rng.uniform(2000, 8000)}},
                "request": {"variants": [{"n_nodes": gang_size}]},
            }],
        }
        submits.append(SubmitSpec(
            at=rng.uniform(0.0, 30.0), job_desc=desc, n_tasks=1,
        ))
    body = {"sim": {"dur_range_ms": [100, 1500], "seed": seed}}
    submits.append(SubmitSpec(
        at=0.0,
        job_desc=_array_desc("filler", filler_tasks, body),
        n_tasks=filler_tasks,
    ))
    return Workload("gang-heavy", submits)


def straggler_tailed(n_tasks: int = 1500, seed: int = 0,
                     split_long: bool = False) -> Workload:
    """Wide and short with a heavy tail: ~2% of tasks run 20-60x the
    median (encoded per-task via the entries channel).

    `split_long` (opt-in, policy A/B) emits the heavy tail as a separate
    ``straggler-long`` job so the runtime predictor can learn a distinct
    per-job-name class and LPT-boost it. Default False keeps the single
    digest-pinned ``straggler-tail`` job; the rng draw sequence is
    identical either way."""
    rng = random.Random(f"tail:{seed}")
    entries = []
    long_entries = []
    for i in range(n_tasks):
        if rng.random() < 0.02:
            e = {"dur_ms": rng.uniform(4000, 12000)}
            (long_entries if split_long else entries).append(e)
        else:
            entries.append({"dur_ms": rng.uniform(50, 300)})

    def _tail_desc(name: str, ents: list) -> dict:
        return {
            "name": name,
            "submit_dir": "/sim",
            "array": {
                "id_range": [0, len(ents)],
                "body": {},
                "entries": ents,
                "request": {"variants": [{"entries": [
                    {"name": "cpus", "amount": 10_000},
                ]}]},
            },
        }

    submits = [SubmitSpec(at=0.0, job_desc=_tail_desc("straggler-tail", entries),
                          n_tasks=len(entries))]
    if long_entries:
        submits.append(SubmitSpec(
            at=0.0, job_desc=_tail_desc("straggler-long", long_entries),
            n_tasks=len(long_entries),
        ))
    return Workload("straggler-tailed", submits)


WORKLOADS = {
    "uniform": uniform_array,
    "bursty": bursty_multi_tenant,
    "dag": deep_dag,
    "gang": gang_heavy,
    "tail": straggler_tailed,
}


def build(name: str, seed: int = 0, **kwargs) -> Workload:
    try:
        factory = WORKLOADS[name]
    except KeyError:
        raise ValueError(
            f"unknown workload {name!r} (have: {', '.join(sorted(WORKLOADS))})"
        ) from None
    return factory(seed=seed, **kwargs)
