"""Journal-replay regression mode: same workload, new scheduler.

A journal is a complete record of WHAT was asked (job-submitted events
carry the verbatim job/array descriptions and their submit clocks).  This
module re-derives a :class:`Workload` from a journal file and re-runs it
in the simulator under any scheduler configuration — "same recorded
workload, new scheduler — compare makespan and decision records" as a
cheap bench row instead of a cluster run.

Task run times: a sim-recorded journal carries them in the task bodies
(``{"sim": ...}``); for journals from real runs the per-job observed mean
run time (task-started → task-finished stamps) is injected instead, so
the replay preserves each job's aggregate execution demand even when the
original bodies were shell commands.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

from hyperqueue_tpu.events.journal import Journal
from hyperqueue_tpu.sim.workloads import SubmitSpec, Workload

logger = logging.getLogger("hq.sim.replay")


def _has_sim_duration(desc: dict) -> bool:
    array = desc.get("array") or {}
    body = array.get("body") or {}
    if isinstance(body, dict) and "sim" in body:
        return True
    for t in desc.get("tasks") or ():
        b = t.get("body") or {}
        if isinstance(b, dict) and "sim" in b:
            return True
    return False


def workload_from_journal(path) -> Workload:
    """Rebuild the submitted workload (arrival times relative to the
    first submit) from a journal's job-submitted events."""
    submits: list[SubmitSpec] = []
    t0: float | None = None
    # per-job observed run times, for journals without sim bodies
    started: dict[tuple[int, int], float] = {}
    durations: dict[int, list[float]] = {}
    per_job: dict[int, list[SubmitSpec]] = {}
    for record in Journal.read_all(path):
        kind = record.get("event")
        if kind == "job-submitted":
            t = float(record.get("time", 0.0))
            if t0 is None:
                t0 = t
            desc = dict(record.get("desc") or {})
            n = int(record.get("n_tasks", 0))
            if n <= 0:
                continue
            desc.setdefault("name", f"job{record.get('job')}")
            desc.setdefault("submit_dir", "/sim")
            spec = SubmitSpec(at=t - t0, job_desc=desc, n_tasks=n)
            submits.append(spec)
            per_job.setdefault(int(record.get("job", 0)), []).append(spec)
        elif kind == "task-started":
            key = (record.get("job"), record.get("task"))
            started[key] = float(record.get("time", 0.0))
        elif kind == "task-finished":
            key = (record.get("job"), record.get("task"))
            t_start = started.pop(key, None)
            if t_start is not None:
                durations.setdefault(int(record.get("job", 0)), []).append(
                    max(float(record.get("time", 0.0)) - t_start, 1e-3)
                )
    for job_id, specs in per_job.items():
        samples = durations.get(job_id)
        for spec in specs:
            if _has_sim_duration(spec.job_desc):
                continue
            mean_ms = (
                sum(samples) / len(samples) * 1e3 if samples else 100.0
            )
            array = spec.job_desc.get("array")
            if array is not None:
                body = dict(array.get("body") or {})
                body["sim"] = {"dur_ms": mean_ms}
                array["body"] = body
            else:
                for t in spec.job_desc.get("tasks") or ():
                    body = dict(t.get("body") or {})
                    body["sim"] = {"dur_ms": mean_ms}
                    t["body"] = body
    return Workload(f"replay:{path}", submits)


@dataclass
class ReplayComparison:
    makespan_a: float
    makespan_b: float
    ticks_a: int
    ticks_b: int
    assigned_a: int
    assigned_b: int

    def summary(self) -> str:
        ratio = (
            self.makespan_b / self.makespan_a if self.makespan_a else 0.0
        )
        return (
            f"makespan {self.makespan_a:.1f}s -> {self.makespan_b:.1f}s "
            f"({ratio:.3f}x), ticks {self.ticks_a} -> {self.ticks_b}, "
            f"assignments {self.assigned_a} -> {self.assigned_b}"
        )


def _decision_totals(decisions: list[dict]) -> tuple[int, int]:
    assigned = 0
    for d in decisions:
        counts = d.get("counts") or {}
        assigned += (counts.get("assigned", 0)
                     + counts.get("gang_assigned", 0)
                     + counts.get("prefilled", 0))
    return len(decisions), assigned


def replay_compare(journal_path, scheduler_a: str, scheduler_b: str,
                   seed: int = 0, n_workers: int = 16,
                   **sim_kwargs) -> ReplayComparison:
    """Run the journal's workload under two scheduler configs and compare
    makespan + decision-record totals."""
    from hyperqueue_tpu.sim.harness import run_scenario

    workload = workload_from_journal(journal_path)
    res_a = run_scenario(workload, seed=seed, n_workers=n_workers,
                         scheduler=scheduler_a, **sim_kwargs)
    res_b = run_scenario(workload, seed=seed, n_workers=n_workers,
                         scheduler=scheduler_b, **sim_kwargs)
    ticks_a, assigned_a = _decision_totals(res_a.decisions)
    ticks_b, assigned_b = _decision_totals(res_b.decisions)
    return ReplayComparison(
        makespan_a=res_a.makespan, makespan_b=res_b.makespan,
        ticks_a=ticks_a, ticks_b=ticks_b,
        assigned_a=assigned_a, assigned_b=assigned_b,
    )
