"""SimWorker: a simulated worker speaking the REAL worker-plane protocol.

One SimWorker is the message-plane shadow of ``worker/runtime.py``: it
authenticates, registers a real ``WorkerConfiguration``, receives the
server's compute/cancel/retract/stop/batch downlink ops, and answers with
the same uplinks (``task_running`` / ``task_finished`` / ``task_failed`` /
``retract_response`` / ``heartbeat`` / ``goodbye``) — but instead of
fork/exec'ing processes it models execution as a virtual-time timer whose
duration comes from the task body.  Everything the SERVER does is
therefore the production code path; only the leaf that would burn CPU is
simulated.

Crash/reconnect semantics mirror the real runtime's
``--on-server-lost reconnect`` contract:

- on connection loss, RUNNING tasks keep executing (their timers keep
  firing) and terminal uplinks accumulate in a bounded done-log;
- queued-but-never-started tasks are parked; the next registration
  reports them as ``blocked`` and the server orders them discarded
  (it re-issues them — the worker must not run a silently-kept copy);
- reconnection re-registers with the ``reattach`` claim (old worker id,
  last known server uid, the (task, instance) set still running), then
  replays the done-log; the server's instance fencing discards stale
  entries;
- ``kill()`` is the unclean death: the link aborts and every running
  execution is lost.

Execution events (start/finish/loss, compute receipt) are reported to the
simulation's invariant monitor — the ground truth the exactly-once checks
run against.
"""

from __future__ import annotations

import asyncio
import logging
import struct
import zlib

from hyperqueue_tpu.transport.framing import _LEN

from hyperqueue_tpu.resources.descriptor import ResourceDescriptor
from hyperqueue_tpu.server.worker import WorkerConfiguration
from hyperqueue_tpu.transport.auth import (
    ROLE_SERVER,
    ROLE_WORKER,
    AuthError,
    do_authentication,
)
from hyperqueue_tpu.utils import clock

logger = logging.getLogger("hq.sim.worker")

# bound on the replayed-on-reconnect terminal-uplink log, mirroring the
# real runtime's bounded done-log
DONE_LOG_CAP = 4096


def task_duration_s(body, entry, task_id: int) -> float:
    """Deterministic virtual run time of one task.

    Priority: per-task entry ``{"dur_ms": X}`` > shared body
    ``{"sim": {"dur_ms": X}}`` > shared body
    ``{"sim": {"dur_range_ms": [lo, hi], "seed": s}}`` hashed per task id
    (CRC32 — stable across processes, unlike ``hash()``) > 100 ms."""
    if isinstance(entry, dict) and "dur_ms" in entry:
        return float(entry["dur_ms"]) / 1e3
    sim = body.get("sim") if isinstance(body, dict) else None
    if isinstance(sim, dict):
        if "dur_ms" in sim:
            return float(sim["dur_ms"]) / 1e3
        rng = sim.get("dur_range_ms")
        if rng:
            lo, hi = float(rng[0]), float(rng[1])
            seed = int(sim.get("seed", 0))
            frac = zlib.crc32(struct.pack("<QQ", task_id, seed)) / 2**32
            return (lo + (hi - lo) * frac) / 1e3
    return 0.1


class _Exec:
    """One running simulated execution."""

    __slots__ = ("task_id", "instance", "cpus", "timer", "msg")

    def __init__(self, task_id, instance, cpus, timer, msg):
        self.task_id = task_id
        self.instance = instance
        self.cpus = cpus
        self.timer = timer
        self.msg = msg


class SimWorker:
    """One simulated worker node (possibly many connection incarnations)."""

    def __init__(
        self,
        sim,
        name: str,
        n_cpus: int = 4,
        group: str = "default",
        heartbeat_secs: float = 8.0,
        reconnect: bool = True,
        reconnect_backoff: float = 0.5,
    ):
        self.sim = sim
        self.name = name
        self.n_cpus = n_cpus
        self.group = group
        self.heartbeat_secs = heartbeat_secs
        self.reconnect = reconnect
        self.reconnect_backoff = reconnect_backoff
        # deterministic per-worker jitter source (seed, worker name)
        import random

        self._rng = random.Random(f"{sim.seed}:{name}")

        self.worker_id = 0          # current server-side id (0 = none)
        self.server_uid = ""
        self.dead = False           # killed / stopped for good
        self.stopping = False       # server ordered a stop
        self.partitioned = False    # network-partitioned from the server
        self.speed = 1.0            # straggler factor (>1 = slower)
        self._conn = None
        self._link = None
        self._task: asyncio.Task | None = None
        self._hb_timer = None

        self.free_cpus = n_cpus * 10_000   # fixed-point, like the wire
        self.running: dict[int, _Exec] = {}
        self.pending: list[dict] = []      # queued compute msgs (FIFO)
        self._done_log: list[dict] = []    # terminal uplinks for replay
        # every (task, instance) this worker ever RECEIVED: the real
        # runtime dedups duplicate (task, instance) computes at receive
        # time — a duplicated delivery must not queue a second copy (a
        # retract would remove one and leave the ghost to run a fenced
        # incarnation later), nor re-run a finished one
        self._seen: set[tuple[int, int]] = set()
        # counters the harness reads
        self.n_started = 0
        self.n_finished = 0
        self.connections = 0

    # --- lifecycle ----------------------------------------------------
    def start(self) -> None:
        self._task = self.sim.loop.create_task(self._run())

    def _config(self) -> WorkerConfiguration:
        return WorkerConfiguration(
            descriptor=ResourceDescriptor.simple_cpus(self.n_cpus),
            hostname=f"sim-{self.name}",
            group=self.group,
            heartbeat_secs=self.heartbeat_secs,
            on_server_lost="reconnect" if self.reconnect else "stop",
        )

    async def _run(self) -> None:
        while not self.dead:
            try:
                await self._session()
            except asyncio.CancelledError:
                raise
            except (AuthError, ConnectionError, OSError,
                    asyncio.IncompleteReadError) as e:
                logger.debug("sim worker %s session ended: %s", self.name, e)
            finally:
                self._teardown_session()
            if self.dead or self.stopping or not self.reconnect:
                break
            # park never-started backlog: the server re-issues those tasks,
            # and the next register reports them as blocked for discard
            await asyncio.sleep(
                self.reconnect_backoff * (0.5 + self._rng.random())
            )

    async def _session(self) -> None:
        if self.partitioned:
            raise ConnectionError("worker is partitioned from the server")
        endpoint = self.sim.connect_worker(self.name)
        self._link = endpoint.link
        conn = await do_authentication(
            endpoint.reader, endpoint.writer, ROLE_WORKER, ROLE_SERVER, None
        )
        register: dict = {"op": "register", "config": self._config().to_wire()}
        if self.worker_id or self.running or self.pending:
            register["reattach"] = {
                "server_uid": self.server_uid,
                "worker_id": self.worker_id,
                "running": [
                    {"id": e.task_id, "instance": e.instance,
                     "variant": e.msg.get("variant", 0)}
                    for e in self.running.values()
                ],
                "blocked": [{"id": m["id"]} for m in self.pending],
            }
        await conn.send(register)
        registered = await conn.recv()
        if registered.get("op") != "registered":
            raise ConnectionError(f"unexpected reply {registered.get('op')!r}")
        self._conn = conn
        self.connections += 1
        self.worker_id = registered["worker_id"]
        self.server_uid = registered.get("server_uid", "")
        discard = set(registered.get("discard") or ())
        # parked backlog is never kept (the server re-issues those ids)
        self.pending.clear()
        for task_id in list(self.running):
            if task_id in discard:
                self._kill_exec(task_id, "discarded at reattach")
        # the dedup memory is CONNECTION-scoped (dup deliveries can only
        # happen within one connection): discarded/parked incarnations
        # may be legitimately re-issued under the same instance by a
        # restored server (lazy tasks re-materialize at instance 0), so
        # only live executions stay fenced
        self._seen = {
            (e.task_id, e.instance) for e in self.running.values()
        }
        # replay the done-log: completions the old server may never have
        # processed; the new server fences stale instances
        for uplink in self._done_log:
            await conn.send(uplink)
        self._arm_heartbeat()
        self.sim.monitor.on_worker_session(
            self.name, self.worker_id, clock.monotonic()
        )
        try:
            while True:
                msg = await conn.recv()
                for sub in (msg["msgs"] if msg.get("op") == "batch"
                            else (msg,)):
                    self._handle(sub)
                if self.stopping:
                    return
        finally:
            self._conn = None
            if self._hb_timer is not None:
                self._hb_timer.cancel()
                self._hb_timer = None

    def _teardown_session(self) -> None:
        if self._link is not None:
            self._link.close()
            self._link = None

    # --- downlink ops -------------------------------------------------
    def _handle(self, msg: dict) -> None:
        op = msg.get("op")
        if op == "compute":
            shared = msg.get("shared_bodies") or []
            now = clock.monotonic()
            for t in msg.get("tasks", ()):
                task = dict(t)
                b = task.pop("b", None)
                task["body"] = shared[b] if b is not None else {}
                key = (task["id"], task.get("instance", 0))
                if key in self._seen:
                    continue  # duplicate delivery: dedup at receive
                self._seen.add(key)
                self.sim.monitor.on_compute_delivered(
                    self.name, self.worker_id, task["id"],
                    task.get("instance", 0), now,
                )
                self.pending.append(task)
            self._fill()
        elif op == "cancel":
            ids = set(msg.get("task_ids", ()))
            self.pending = [m for m in self.pending if m["id"] not in ids]
            for task_id in list(self.running):
                if task_id in ids:
                    self._kill_exec(task_id, "canceled")
            self._fill()
        elif op == "retract":
            for task_id, instance in msg.get("tasks", ()):
                ok = False
                for i, m in enumerate(self.pending):
                    if m["id"] == task_id and m.get("instance") == instance:
                        del self.pending[i]
                        ok = True
                        break
                self._send({"op": "retract_response", "id": task_id,
                            "instance": instance, "ok": ok})
        elif op == "stop":
            self.stopping = True
            self._send({"op": "goodbye"})
            # close after the goodbye drains (same loop turn ordering)
            if self._link is not None:
                self._link.close()
        elif op in ("set_overview_override", "redirect"):
            pass  # no hardware overviews / federation in the simulator
        else:
            logger.warning("sim worker %s: unknown op %r", self.name, op)

    # --- execution model ----------------------------------------------
    def _fill(self) -> None:
        """Start queued tasks while resources fit (FIFO, like the real
        runtime's resource-gated launch queue).  While disconnected the
        backlog stays PARKED (never started): the next registration
        reports it as blocked and the server re-issues those tasks."""
        if self._conn is None:
            return
        while self.pending and not self.stopping:
            msg = self.pending[0]
            cpus = self._cpus_of(msg)
            if msg.get("n_nodes", 0) == 0 and cpus > self.free_cpus:
                break
            self.pending.pop(0)
            self._start_exec(msg, cpus)

    def _cpus_of(self, msg: dict) -> int:
        for entry in msg.get("entries") or ():
            if entry.get("name") == "cpus":
                amount = int(entry.get("amount", 10_000))
                # ALL policy ships amount 0: take the whole pool
                return amount if amount > 0 else self.n_cpus * 10_000
        return 10_000

    def _start_exec(self, msg: dict, cpus: int) -> None:
        task_id = msg["id"]
        instance = msg.get("instance", 0)
        prior = self.running.get(task_id)
        if prior is not None:
            # a NEWER instance supersedes a local incarnation the server
            # already fenced out (its completion would be discarded anyway)
            if prior.instance >= instance:
                return
            self._kill_exec(task_id, "superseded by newer instance")
        if msg.get("n_nodes", 0) == 0:
            self.free_cpus -= cpus
        else:
            cpus = 0  # gang root: the server reserved whole workers
        dur = task_duration_s(msg.get("body"), msg.get("entry"), task_id)
        dur *= self.speed
        timer = self.sim.loop.call_later(dur, self._finish_exec, task_id)
        self.running[task_id] = _Exec(task_id, instance, cpus, timer, msg)
        self.n_started += 1
        self.sim.monitor.on_exec_started(
            self.name, self.worker_id, task_id, instance, clock.monotonic()
        )
        self._send({"op": "task_running", "id": task_id,
                    "instance": instance})

    def _finish_exec(self, task_id: int) -> None:
        ex = self.running.pop(task_id, None)
        if ex is None:
            return
        self.free_cpus += ex.cpus
        body = ex.msg.get("body") or {}
        sim = body.get("sim") if isinstance(body, dict) else None
        fail_ids = (sim or {}).get("fail_ids") or ()
        failed = (task_id & 0xFFFFFFFF) in fail_ids
        self.n_finished += 1
        self.sim.monitor.on_exec_finished(
            self.name, self.worker_id, task_id, ex.instance,
            clock.monotonic(), failed=failed,
        )
        if failed:
            uplink = {"op": "task_failed", "id": task_id,
                      "instance": ex.instance, "error": "sim-injected failure"}
        else:
            uplink = {"op": "task_finished", "id": task_id,
                      "instance": ex.instance}
        self._log_done(uplink)
        self._send(uplink)
        self._fill()

    def _kill_exec(self, task_id: int, reason: str) -> None:
        ex = self.running.pop(task_id, None)
        if ex is None:
            return
        ex.timer.cancel()
        self.free_cpus += ex.cpus
        self.sim.monitor.on_exec_lost(
            self.name, self.worker_id, task_id, ex.instance,
            clock.monotonic(), reason,
        )

    def _log_done(self, uplink: dict) -> None:
        self._done_log.append(uplink)
        if len(self._done_log) > DONE_LOG_CAP:
            del self._done_log[: len(self._done_log) - DONE_LOG_CAP]

    # --- uplink -------------------------------------------------------
    def _send(self, msg: dict) -> None:
        """Synchronous uplink: the in-memory transport's write never
        blocks, so frames go out inline (encode + two writes) in exactly
        the order the model produced them — no per-message task churn."""
        conn = self._conn
        if conn is None:
            return  # disconnected: terminal ops live in the done-log
        try:
            data = conn.encode(msg)
            conn.writer.write(_LEN.pack(len(data)))
            conn.writer.write(data)
        except (ConnectionError, OSError):
            pass  # the recv loop notices the dead link

    def _arm_heartbeat(self) -> None:
        if self._hb_timer is not None:
            self._hb_timer.cancel()
        loop = self.sim.loop

        def beat() -> None:
            if self._conn is not None and not self.dead:
                self._send({"op": "heartbeat"})
                self._hb_timer = loop.call_later(self.heartbeat_secs, beat)

        self._hb_timer = loop.call_later(self.heartbeat_secs, beat)

    # --- fault levers ---------------------------------------------------
    def kill(self) -> None:
        """Unclean death: the link aborts, every execution is lost."""
        self.dead = True
        for task_id in list(self.running):
            self._kill_exec(task_id, "worker killed")
        self.pending.clear()
        self._done_log.clear()
        self._seen.clear()
        if self._hb_timer is not None:
            self._hb_timer.cancel()
            self._hb_timer = None
        if self._link is not None:
            self._link.abort()
            self._link = None
        if self._task is not None:
            self._task.cancel()

    def revive(self) -> "SimWorker":
        """A fresh worker process on the same simulated node (same name
        suffix convention, new registration)."""
        return self.sim.add_worker(
            name=f"{self.name}+", n_cpus=self.n_cpus, group=self.group,
            heartbeat_secs=self.heartbeat_secs, reconnect=self.reconnect,
        )

    def partition(self, on: bool = True) -> None:
        """Partition (or heal) this worker: the current link buffers all
        traffic and reconnect attempts fail until healed."""
        self.partitioned = bool(on)
        if self._link is not None:
            self._link.partition(on)

    async def wait_stopped(self) -> None:
        if self._task is not None:
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
