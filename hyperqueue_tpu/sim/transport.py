"""In-memory duplex streams: the simulator's wire.

A :func:`duplex` pair behaves like two ends of a TCP connection — real
``asyncio.StreamReader``/``StreamWriter`` objects, so the server's
connection handlers, the auth handshake, and the length-delimited msgpack
framing all run UNCHANGED — but bytes move by feeding the peer's protocol
inside the same event loop.  Ordering per direction is FIFO by
construction; no kernel buffering, no partial reads at nondeterministic
boundaries.

Each end owns a :class:`SimLink` with the sim-native network fault levers:

- ``cut`` — a partition: writes BUFFER (TCP would retransmit, not lose
  them) while both ends believe the connection is up; healing flushes the
  backlog in order, and a partition that outlasts the heartbeat timeout
  gets the connection reaped server-side like a real one;
- ``latency`` — per-byte-stream one-way delay, delivered through virtual
  timers with FIFO preserved (a latency drop mid-stream cannot reorder
  frames);
- ``close()`` — orderly teardown: the peer reads EOF, like a FIN;
- ``abort()`` — teardown that also drops queued-but-undelivered bytes,
  like a process dying with unflushed socket buffers.

Late deliveries into a closed end are dropped (a real kernel drops
packets for a closed socket), so an abrupt kill never feeds a dead
reader.
"""

from __future__ import annotations

import asyncio


class _SimTransport(asyncio.Transport):
    """Write-side of one direction; delivery goes to the peer protocol."""

    def __init__(self, loop, link: "SimLink", label: str):
        super().__init__()
        self._loop = loop
        self._link = link
        self.label = label
        self._protocol = None          # OWN side's protocol (for close)
        self.peer: "_SimTransport | None" = None
        self.closed = False
        self._eof_sent = False
        # FIFO delivery under latency: (deliver_at, data) queue + the
        # timestamp of the newest scheduled delivery, so a latency change
        # mid-stream can never reorder two writes
        self._last_deliver_at = 0.0

    # --- asyncio.Transport surface the streams layer touches ----------
    def set_protocol(self, protocol) -> None:
        self._protocol = protocol

    def get_protocol(self):
        return self._protocol

    def get_extra_info(self, name, default=None):
        if name == "peername":
            return ("sim", self.label)
        return default

    def is_closing(self) -> bool:
        return self.closed

    def pause_reading(self) -> None:  # flow control: a no-op in memory
        pass

    def resume_reading(self) -> None:
        pass

    def write(self, data) -> None:
        if self.closed or self.peer is None:
            return
        link = self._link
        data = bytes(data)
        if link.cut:
            # partitioned: the bytes are in flight, not lost — TCP would
            # retransmit them until the window heals or the peer resets
            link.buffer.append((self, data))
            link.buffered_bytes += len(data)
            return
        if link.latency <= 0.0 and self._last_deliver_at <= self._loop.time():
            self._deliver(data)
            return
        deliver_at = max(
            self._loop.time() + link.latency, self._last_deliver_at
        )
        self._last_deliver_at = deliver_at
        self._loop.call_at(deliver_at, self._deliver, data)

    def _deliver(self, data: "bytes | None") -> None:
        """Deliver one chunk to the peer; None is the EOF marker (EOF
        rides the same ordered channel as data, so a close can never
        outrun bytes still queued behind a partition or latency)."""
        peer = self.peer
        if peer is None or peer.closed:
            return  # packets to a closed socket are dropped
        if data is None:
            peer._protocol.eof_received()
            return
        try:
            peer._protocol.data_received(data)
        except Exception:  # noqa: BLE001 - a reader torn down mid-flight
            pass           # behaves like a closed socket: drop

    def write_eof(self) -> None:
        if self._eof_sent or self.peer is None:
            return
        self._eof_sent = True
        link = self._link
        if link.cut:
            # the FIN queues behind the partitioned backlog
            link.buffer.append((self, None))
            return
        if link.latency > 0.0 or self._last_deliver_at > self._loop.time():
            deliver_at = max(
                self._loop.time() + link.latency, self._last_deliver_at
            )
            self._last_deliver_at = deliver_at
            self._loop.call_at(deliver_at, self._deliver, None)
            return
        self._loop.call_soon(self._deliver, None)

    def can_write_eof(self) -> bool:
        return True

    def close(self) -> None:
        """Orderly close of this end: own protocol sees connection_lost,
        the peer reads EOF after anything already in flight."""
        if self.closed:
            return
        self.closed = True
        self.write_eof()
        self._loop.call_soon(self._connection_lost)

    def abort(self) -> None:
        """Abrupt close: undelivered bytes are lost (scheduled deliveries
        find this end closed and drop), peer sees EOF immediately."""
        if self.closed:
            return
        self.closed = True
        if self.peer is not None:
            self.peer.closed = True
            # the peer's reader gets EOF so its recv loop unblocks
            self._loop.call_soon(self._peer_eof_abort)
        self._loop.call_soon(self._connection_lost)

    def _peer_eof_abort(self) -> None:
        peer = self.peer
        if peer is not None:
            try:
                peer._protocol.eof_received()
            except Exception:  # noqa: BLE001 - peer may be torn down
                pass

    def _connection_lost(self) -> None:
        if self._protocol is not None:
            try:
                self._protocol.connection_lost(None)
            except Exception:  # noqa: BLE001 - teardown must not raise
                pass


class SimLink:
    """Shared fault state of one duplex connection (both directions)."""

    __slots__ = ("name", "cut", "latency", "buffer", "buffered_bytes",
                 "ends")

    def __init__(self, name: str, latency: float = 0.0):
        self.name = name
        self.cut = False
        self.latency = float(latency)
        self.buffer: list = []      # (transport, data) held by a partition
        self.buffered_bytes = 0
        self.ends: tuple = ()

    def partition(self, on: bool = True) -> None:
        self.cut = bool(on)
        if not self.cut and self.buffer:
            # heal: the retransmit backlog lands in order
            backlog, self.buffer = self.buffer, []
            self.buffered_bytes = 0
            for transport, data in backlog:
                transport._deliver(data)

    def close(self) -> None:
        for end in self.ends:
            end.transport.close()

    def abort(self) -> None:
        # an abort mid-partition loses the in-flight backlog, like a
        # connection reset while the window was dark
        self.buffer.clear()
        self.buffered_bytes = 0
        for end in self.ends:
            end.transport.abort()

    @property
    def alive(self) -> bool:
        return bool(self.ends) and not any(e.transport.closed
                                           for e in self.ends)


class SimEndpoint:
    """One end: (reader, writer) plus the transport underneath."""

    __slots__ = ("reader", "writer", "transport", "link")

    def __init__(self, reader, writer, transport, link):
        self.reader = reader
        self.writer = writer
        self.transport = transport
        self.link = link


# big limit: compute batches for 512-task prefills are single frames; the
# default 64 KiB StreamReader limit only gates readuntil, but keep the
# flow-control ceiling far away regardless
_READER_LIMIT = 1 << 30


def duplex(loop, name: str = "link",
           latency: float = 0.0) -> tuple[SimEndpoint, SimEndpoint]:
    """A connected in-memory stream pair (a-end, b-end)."""
    link = SimLink(name, latency=latency)

    def make_end(label: str) -> SimEndpoint:
        reader = asyncio.StreamReader(limit=_READER_LIMIT, loop=loop)
        protocol = asyncio.StreamReaderProtocol(reader, loop=loop)
        transport = _SimTransport(loop, link, label)
        transport.set_protocol(protocol)
        protocol.connection_made(transport)
        writer = asyncio.StreamWriter(transport, protocol, reader, loop)
        return SimEndpoint(reader, writer, transport, link)

    a = make_end(f"{name}:a")
    b = make_end(f"{name}:b")
    a.transport.peer = b.transport
    b.transport.peer = a.transport
    link.ends = (a, b)
    return a, b
