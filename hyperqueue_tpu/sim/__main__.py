"""``python -m hyperqueue_tpu.sim`` — seed-reproducible cluster scenarios.

Run a synthetic workload under a seeded fault schedule on the virtual
clock, with invariants checked throughout::

    python -m hyperqueue_tpu.sim --seed 7
    python -m hyperqueue_tpu.sim --seed 7 --workload bursty --workers 64 \
        --tasks 20000 --fault-rate 0.05 --server-kills 2

On an invariant violation the harness re-runs the scenario with binary-
searched fault-schedule prefixes to find the minimal failing prefix and
prints the one-line repro.  ``--replay JOURNAL --compare-scheduler S``
drives the journal-replay regression mode instead.

For cross-invocation bit-reproducibility set ``PYTHONHASHSEED`` (a few
str-set iteration orders inside the server depend on it).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hyperqueue_tpu.sim",
        description="deterministic cluster simulator (virtual clock, "
                    "seeded faults, invariant checking)",
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--workload", default="uniform",
                        help="uniform | bursty | dag | gang | tail")
    parser.add_argument("--workers", type=int, default=32)
    parser.add_argument("--worker-cpus", type=int, default=4)
    parser.add_argument("--tasks", type=int, default=2000,
                        help="task count for sized workloads")
    parser.add_argument("--dur-ms", type=float, default=1000.0,
                        help="median task duration (uniform workload)")
    parser.add_argument("--fault-rate", type=float, default=0.0,
                        help="faults per worker-second; 0 = fault-free")
    parser.add_argument("--server-kills", type=int, default=1,
                        help="server kill -9 + restore events in the "
                             "schedule (with --fault-rate > 0)")
    parser.add_argument("--horizon", type=float, default=None,
                        help="virtual deadline (default: auto)")
    parser.add_argument("--scheduler", default="greedy-numpy")
    parser.add_argument("--no-bisect", action="store_true",
                        help="skip minimal-prefix bisection on failure")
    parser.add_argument("--replay", metavar="JOURNAL",
                        help="journal-replay mode: rebuild the workload "
                             "from this journal")
    parser.add_argument("--compare-scheduler", default=None,
                        help="with --replay: run twice and compare "
                             "makespan/decisions between --scheduler and "
                             "this one")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable result line")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.ERROR,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )

    from hyperqueue_tpu.sim import (
        FaultSchedule,
        InvariantViolation,
        SimDeadlockError,
        Simulation,
        bisect_failure,
        build,
        run_scenario,
    )

    if args.replay:
        from hyperqueue_tpu.sim.replay import (
            replay_compare,
            workload_from_journal,
        )

        if args.compare_scheduler:
            cmp_result = replay_compare(
                args.replay, args.scheduler, args.compare_scheduler,
                seed=args.seed, n_workers=args.workers,
            )
            print(cmp_result.summary())
            return 0
        workload = workload_from_journal(args.replay)
    else:
        sizing = {
            "uniform": {"n_tasks": args.tasks, "dur_ms": args.dur_ms},
            "bursty": {"tasks_per_burst": max(args.tasks // 12, 1)},
            "dag": {"width": max(args.tasks // 12, 4)},
            "gang": {"filler_tasks": args.tasks},
            "tail": {"n_tasks": args.tasks},
        }.get(args.workload, {})
        workload = build(args.workload, seed=args.seed, **sizing)

    worker_names = [f"w{i}" for i in range(args.workers)]
    faults = None
    if args.fault_rate > 0:
        # a rough virtual-makespan guess keeps faults inside the run
        guess = max(
            workload.horizon_hint + args.tasks * args.dur_ms
            / 1e3 / max(args.workers * args.worker_cpus, 1), 30.0,
        )
        faults = FaultSchedule.generate(
            args.seed, horizon=guess, worker_names=worker_names,
            rate=args.fault_rate, server_kills=args.server_kills,
        )

    def make_sim(schedule):
        return Simulation(
            workload, seed=args.seed, n_workers=args.workers,
            worker_cpus=args.worker_cpus, faults=schedule,
            scheduler=args.scheduler, horizon=args.horizon,
        )

    try:
        result = run_scenario(
            workload, seed=args.seed, n_workers=args.workers,
            worker_cpus=args.worker_cpus, faults=faults,
            scheduler=args.scheduler, horizon=args.horizon,
        )
    except (InvariantViolation, SimDeadlockError, TimeoutError,
            asyncio.TimeoutError) as e:  # asyncio alias != builtin on 3.10
        print(f"FAIL: {e}", file=sys.stderr)
        if faults is not None and not args.no_bisect and len(faults):
            k, prefix = bisect_failure(make_sim, faults)
            print(f"minimal failing fault prefix: {k} event(s)",
                  file=sys.stderr)
            for line in prefix:
                print(f"  {line}", file=sys.stderr)
        print(
            "repro: python -m hyperqueue_tpu.sim "
            f"--seed {args.seed} --workload {args.workload} "
            f"--workers {args.workers} --tasks {args.tasks} "
            f"--fault-rate {args.fault_rate} "
            f"--server-kills {args.server_kills}",
            file=sys.stderr,
        )
        return 1

    if args.as_json:
        print(json.dumps({
            "seed": result.seed,
            "workload": result.workload,
            "n_tasks": result.n_tasks,
            "makespan_virtual_s": round(result.makespan, 3),
            "wall_s": round(result.wall_s, 3),
            "virtual_tasks_per_wall_s": round(
                result.virtual_tasks_per_wall_s, 1
            ),
            "server_boots": result.server_boots,
            "audit": result.audit,
            "decision_digest": result.decision_digest,
            "journal_digest": result.journal_digest,
        }))
    else:
        print(
            f"OK seed={result.seed} workload={result.workload} "
            f"tasks={result.n_tasks} finished={result.audit['finished']} "
            f"makespan={result.makespan:.1f}s(virtual) "
            f"wall={result.wall_s:.2f}s boots={result.server_boots} "
            f"executions={result.audit['executions']}"
        )
        print(f"decision digest {result.decision_digest[:16]}… "
              f"journal digest {result.journal_digest[:16]}…")
    return 0


if __name__ == "__main__":
    sys.exit(main())
