"""Federated simulation: M shard servers + the migration driver on ONE
virtual clock.

ISSUE 17's chaos gate needs to interleave a live job migration with
kill -9 of the source, the destination, or the driver at every protocol
phase — and prove exactly-once execution and single ownership each time.
Process-level chaos tests can hit a handful of interleavings per second;
this module runs the whole federation (every shard a real ``Server``,
the real ``drive_migration_async`` driver, real journals and the real
ownership log) inside one :class:`~hyperqueue_tpu.sim.loop.SimEventLoop`,
so a scenario explores a kill site per virtual millisecond and the run
is a deterministic function of (scenario, seed, rules).

Kill model: ONE global chaos kill handler serves every shard. A chaos
``action: "kill"`` rule fires inside whichever call stack reached the
site; :func:`chaos.last_ctx` says whose — a ``Server`` instance means
"this shard dies now" (its journal appender is abandoned mid-buffer, its
links abort, a supervisor restores it after a delay), the string
``"coordinator"`` means the migration driver dies (its coroutine unwinds
with :class:`SimKilled`; a later :meth:`FederatedSimulation.recover`
re-drives the intent from the ownership log, exactly like
``hq fleet migrate --recover`` after a coordinator crash).

Invariants are FLEET-SCOPED: one shared monitor sees every shard's
journaled events and every simulated execution, so a task that slips
through a migration twice — once on each side — is caught the moment the
second ``(task, instance)`` starts, and the final audit counts terminal
records across ALL shard journals plus exactly one live owner per job.
"""

from __future__ import annotations

import asyncio
import logging
import shutil
import tempfile
import time as _walltime
from pathlib import Path

from hyperqueue_tpu.server.bootstrap import Server
from hyperqueue_tpu.server.federation import (
    MigrationError,
    drive_migration_async,
)
from hyperqueue_tpu.sim.client import SimClient, SimClientError, SimSubmitStream
from hyperqueue_tpu.sim.harness import SimKilled
from hyperqueue_tpu.sim.invariants import InvariantMonitor, InvariantViolation
from hyperqueue_tpu.sim.loop import SimClock, SimEventLoop
from hyperqueue_tpu.sim.transport import duplex
from hyperqueue_tpu.sim.worker import SimWorker
from hyperqueue_tpu.utils import chaos, clock, serverdir
from hyperqueue_tpu.utils import trace as trace_mod
from hyperqueue_tpu.utils.lease import LeaseHeldError
from hyperqueue_tpu.utils.metrics import REGISTRY
from hyperqueue_tpu.utils.ownership import OwnershipStore

logger = logging.getLogger("hq.sim.federation")


class FederatedMonitor(InvariantMonitor):
    """The single-server monitor, made ownership-aware.

    Execution/fence/exactly-once tracking is already keyed by global
    ``(job << 32) | task`` ids, so sharing one monitor across shards
    needs no change there. Only the restore-time ack-durability check
    must learn routing: an acked job is owed to its CURRENT owner shard,
    not to whichever shard happens to be restoring."""

    def _owned_here(self, server, job_id) -> bool:
        if job_id is None:
            return False
        if job_id in server.migrated_out or job_id in server.migrating_out:
            return False  # sealed/shipped away: the destination answers
        try:
            root = self.sim.root
            owner = OwnershipStore(root).load().shard_for_job(int(job_id))
        except OSError:
            owner = (int(job_id) - 1) % max(self.sim.shard_count, 1)
        return owner == server.shard_id

    def check_restored_server(self, server) -> None:
        for uid, indexes in self.acked_chunks.items():
            job_id = self.chunk_jobs.get(uid)
            if not self._owned_here(server, job_id):
                continue
            job = server.jobs.jobs.get(job_id)
            if job is None:
                self._fail(
                    f"ack-durability violation: job {job_id} (stream "
                    f"{uid}) was acked but is unknown on its owner shard "
                    f"{server.shard_id} after restore"
                )
            stream = job.streams.get(uid)
            applied = stream["applied"] if stream else set()
            missing = indexes - set(applied)
            if missing and not job.is_terminated():
                self._fail(
                    f"ack-durability violation: stream {uid} chunks "
                    f"{sorted(missing)} were acked but not applied on "
                    f"shard {server.shard_id} after restore"
                )
        for job_id in self.acked_jobs:
            if not self._owned_here(server, job_id):
                continue
            if job_id not in server.jobs.jobs:
                self._fail(
                    f"ack-durability violation: job {job_id} was acked "
                    f"but is unknown on its owner shard "
                    f"{server.shard_id} after restore"
                )


class _ShardSim:
    """One shard's harness surface: exactly the attribute set SimWorker
    and SimClient read from a ``Simulation`` (loop / seed / monitor /
    connect_* / add_worker), plus this shard's kill/restore lifecycle."""

    def __init__(self, fed: "FederatedSimulation", shard_id: int):
        self.fed = fed
        self.shard_id = shard_id
        self.seed = fed.seed
        self.monitor = fed.monitor
        self.server_dir = serverdir.shard_path(fed.root, shard_id)
        self.journal_path = self.server_dir / "journal.bin"
        self.server: Server | None = None
        self.server_boots = 0
        self.workers: dict[str, SimWorker] = {}
        self.client = SimClient(self, name=f"driver-s{shard_id}")
        self._links: list = []
        self._event_tap_task = None
        self._down: asyncio.Event | None = None
        self._restore_delay = fed.restore_delay

    @property
    def loop(self):
        return self.fed.loop

    # --- connection points (SimWorker / SimClient call these) ----------
    def connect_worker(self, name: str):
        if self.server is None:
            raise ConnectionError(f"shard {self.shard_id} is down")
        a, b = duplex(self.loop, name=f"s{self.shard_id}-w-{name}")
        self._links.append(a.link)
        self.server.accept_worker(b.reader, b.writer)
        return a

    def connect_client(self, name: str):
        if self.server is None:
            raise ConnectionError(f"shard {self.shard_id} is down")
        a, b = duplex(self.loop, name=f"s{self.shard_id}-c-{name}")
        self._links.append(a.link)
        self.server.accept_client(b.reader, b.writer)
        return a

    def add_worker(self, name: str | None = None, **kwargs) -> SimWorker:
        name = name or f"s{self.shard_id}w{len(self.workers)}"
        worker = SimWorker(
            self, name,
            n_cpus=kwargs.pop("n_cpus", self.fed.worker_cpus),
            group=kwargs.pop("group", f"shard{self.shard_id}"),
            heartbeat_secs=kwargs.pop(
                "heartbeat_secs", self.fed.heartbeat_secs
            ),
            **kwargs,
        )
        self.workers[name] = worker
        worker.start()
        return worker

    # --- lifecycle ------------------------------------------------------
    async def start_server(self) -> Server:
        kwargs = dict(
            server_dir=self.server_dir,
            host=f"sim-shard-{self.shard_id}",
            disable_client_auth=True,
            disable_worker_auth=True,
            scheduler=self.fed.scheduler,
            schedule_min_delay=self.fed.schedule_min_delay,
            journal_path=self.journal_path,
            reattach_timeout=self.fed.reattach_timeout,
            solver_watchdog_timeout=0.0,
            client_plane="reactor",
            journal_plane="reactor",
            fanout_senders=0,
            memory_transport=True,
            lease_timeout=self.fed.lease_timeout,
            shard_id=self.shard_id,
            shard_count=self.fed.shard_count,
            federation_root=self.fed.root,
            failover_watch=False,
        )
        kwargs.update(self.fed.server_kwargs)
        server = Server(**kwargs)
        await server.start()
        self.server = server
        self.server_boots += 1
        self._links = []
        tap: asyncio.Queue = asyncio.Queue()
        server._event_listeners.append(tap)
        self._event_tap_task = self.loop.create_task(self._drain_tap(tap))
        if server.n_boots > 1:
            self.monitor.check_restored_server(server)
        return server

    async def _drain_tap(self, tap: asyncio.Queue) -> None:
        while True:
            record = await tap.get()
            self.monitor.on_event(record)

    def kill_now(self) -> None:
        """kill -9 this shard's incarnation (mirrors the single-server
        harness: unflushed journal tail lost, links aborted)."""
        server = self.server
        if server is None:
            return
        self.server = None
        server._event_listeners.clear()
        server._subscribers.clear()
        if self._event_tap_task is not None:
            self._event_tap_task.cancel()
            self._event_tap_task = None
        if server.journal is not None:
            server.journal.kill()
            server.journal = None
        server.jplane = None
        for t in (list(server._tasks) + list(server._client_tasks)
                  + list(server._conn_tasks)):
            t.cancel()
        if server.autoalloc is not None:
            server.autoalloc.stop()
        if server._metrics_hook is not None:
            REGISTRY.remove_collect_hook(server._metrics_hook)
            server._metrics_hook = None
        for link in self._links:
            link.abort()
        self._links = []
        if self._down is not None:
            self._down.set()
        logger.info("sim: shard %d killed at t=%.3f",
                    self.shard_id, clock.monotonic())

    async def supervisor(self) -> None:
        self._down = asyncio.Event()
        while True:
            await self._down.wait()
            self._down.clear()
            if self.fed._stopping:
                return
            await asyncio.sleep(self._restore_delay)
            self._restore_delay = self.fed.restore_delay
            while not self.fed._stopping:
                try:
                    await self.start_server()
                except LeaseHeldError:
                    # the killed incarnation's lease is not yet stale —
                    # a real restarted process waits it out the same way
                    await asyncio.sleep(0.5)
                    continue
                logger.info("sim: shard %d restored at t=%.3f",
                            self.shard_id, clock.monotonic())
                break


class FederatedSimulation:
    """M shard servers + per-shard workers + the migration driver, on one
    virtual clock, under one chaos plan and one fleet-wide monitor.

    Usage::

        fed = FederatedSimulation(shard_count=2, rules=[
            {"site": "server.event", "event": "migration-out",
             "shard": 0, "action": "kill", "times": 1},
        ])
        result = fed.run(scenario)   # async def scenario(fed): ...

    The scenario drives submits (:meth:`submit` / :meth:`stream`),
    migrations (:meth:`migrate` / :meth:`recover`), shard kills
    (:meth:`kill_shard`) and arbitrary RPCs (:meth:`rpc`); ``run``
    quiesces every submitted job, audits the fleet, and tears down."""

    def __init__(
        self,
        shard_count: int = 2,
        seed: int = 0,
        n_workers_per_shard: int = 4,
        worker_cpus: int = 4,
        rules: list[dict] | None = None,
        root: Path | None = None,
        scheduler: str = "greedy-numpy",
        schedule_min_delay: float = 0.01,
        heartbeat_secs: float = 8.0,
        reattach_timeout: float = 5.0,
        restore_delay: float = 1.0,
        lease_timeout: float = 3.0,
        horizon: float = 1800.0,
        server_kwargs: dict | None = None,
    ):
        self.shard_count = max(int(shard_count), 1)
        self.seed = seed
        self.n_workers_per_shard = n_workers_per_shard
        self.worker_cpus = worker_cpus
        self.rules = list(rules or [])
        self.scheduler = scheduler
        self.schedule_min_delay = schedule_min_delay
        self.heartbeat_secs = heartbeat_secs
        self.reattach_timeout = reattach_timeout
        self.restore_delay = restore_delay
        self.lease_timeout = lease_timeout
        self.horizon = horizon
        self.server_kwargs = dict(server_kwargs or {})
        self._own_dir = root is None
        self.root = Path(root or tempfile.mkdtemp(prefix="hq-fedsim-"))

        self.loop: SimEventLoop | None = None
        # gang checks read .server off the monitor's sim; fleet-scoped
        # monitoring has no single server, so they no-op here
        self.server = None
        self.monitor = FederatedMonitor(self)
        self.shards: list[_ShardSim] = []
        self.expected_tasks: dict[int, int] = {}
        self.driver_kills = 0
        self._stopping = False
        self._supervisors: list = []
        self.wall_s = 0.0

    # --- scenario surface ------------------------------------------------
    def store(self) -> OwnershipStore:
        return OwnershipStore(self.root)

    async def rpc(self, shard_id: int, msg: dict, retries: int = 400,
                  retry_delay: float = 0.25) -> dict:
        """Raw request against one shard; a connection that dies with a
        shard kill is retried against the restored incarnation. Error
        replies are RETURNED (the migration driver reads them), not
        raised."""
        from hyperqueue_tpu.transport.auth import AuthError

        client = self.shards[shard_id].client
        last: Exception | None = None
        async with client._lock:
            for _ in range(retries):
                try:
                    conn = await client._ensure_conn()
                    await conn.send(msg)
                    return await conn.recv()
                except (ConnectionError, OSError, AuthError,
                        asyncio.IncompleteReadError) as e:
                    last = e
                    client.drop_connection()
                    await asyncio.sleep(retry_delay)
        raise SimClientError(f"shard {shard_id} rpc failed: {last}")

    async def submit(self, shard_id: int, job_desc: dict) -> dict:
        reply = await self.shards[shard_id].client.submit(job_desc)
        self.expected_tasks[reply["job_id"]] = (
            self.expected_tasks.get(reply["job_id"], 0)
            + reply.get("n_tasks", 0)
        )
        return reply

    def stream(self, shard_id: int, uid: str, header: dict) \
            -> SimSubmitStream:
        return SimSubmitStream(self.shards[shard_id].client, uid=uid,
                               header=dict(header))

    def track(self, job_id: int, n_tasks: int) -> None:
        """Register chunk-streamed tasks with the quiescence audit."""
        self.expected_tasks[job_id] = (
            self.expected_tasks.get(job_id, 0) + n_tasks
        )

    async def migrate(self, job_id: int, to_shard: int,
                      mig: str | None = None) -> dict | None:
        """Drive one migration; ``None`` means the DRIVER was chaos-killed
        mid-protocol (the intent stays in the ownership log for
        :meth:`recover`)."""
        try:
            return await drive_migration_async(
                self.root, job_id, to_shard, mig=mig, store=self.store(),
                rpc=self.rpc,
            )
        except SimKilled:
            self.driver_kills += 1
            logger.info("sim: migration driver killed (job %d)", job_id)
            return None

    async def recover(self) -> list[dict]:
        """Re-drive every in-flight intent in the ownership log — the
        async twin of ``recover_migrations`` (which wraps asyncio.run and
        cannot nest inside the sim loop)."""
        out = []
        store = self.store()
        for rec in store.load().in_flight():
            try:
                out.append(await drive_migration_async(
                    self.root, int(rec["job"]), int(rec["to"]),
                    mig=rec["mig"], store=store, rpc=self.rpc,
                    from_shard=int(rec["from"]),
                ))
            except (MigrationError, SimKilled) as e:
                logger.warning("sim: re-drive of %s failed: %s",
                               rec.get("mig"), e)
        return out

    async def kill_shard(self, shard_id: int,
                         restore_after: float | None = None) -> None:
        shard = self.shards[shard_id]
        if restore_after is not None:
            shard._restore_delay = restore_after
        shard.kill_now()
        await asyncio.sleep(0)

    async def add_shard(self, n_workers: int | None = None) -> int:
        """Online N -> N+1: boot a brand-new shard against the same
        federation root (its start grows the descriptor and journals the
        shard-add in the ownership log) and give it workers. The existing
        shards keep running — no restart anywhere. Returns the new id."""
        new_id = len(self.shards)
        self.shard_count = new_id + 1
        shard = _ShardSim(self, new_id)
        self.shards.append(shard)
        await shard.start_server()
        self._supervisors.append(self.loop.create_task(shard.supervisor()))
        for _ in range(self.n_workers_per_shard
                       if n_workers is None else n_workers):
            shard.add_worker()
        return new_id

    async def wait_job(self, job_id: int, retries: int = 400) -> dict:
        """job_wait routed at the job's CURRENT owner — re-resolving
        through the ownership log on every wrong-shard redirect, the way
        a FederatedSession client does."""
        last: Exception | None = None
        for _ in range(retries):
            try:
                owner = self.store().load().shard_for_job(job_id)
            except OSError:
                owner = (job_id - 1) % self.shard_count
            try:
                return await self.rpc(
                    owner, {"op": "job_wait", "job_ids": [job_id]},
                )
            except SimClientError as e:
                last = e
            await asyncio.sleep(0.25)
        raise SimClientError(f"job_wait({job_id}) failed: {last}")

    async def wait_all(self) -> None:
        for job_id in sorted(self.expected_tasks):
            reply = await self.wait_job(job_id)
            if reply.get("op") == "error":
                raise SimClientError(
                    f"job_wait({job_id}) errored: {reply.get('message')}"
                )

    # --- chaos ------------------------------------------------------------
    def chaos_kill_handler(self) -> None:
        ctx = chaos.last_ctx()
        if isinstance(ctx, Server):
            for shard in self.shards:
                if shard.server is ctx:
                    shard.kill_now()
                    break
        # ctx == "coordinator" (or unknown): only the injecting stack —
        # the migration driver — dies; every shard keeps running
        raise SimKilled("chaos kill")

    # --- main --------------------------------------------------------------
    def run(self, scenario) -> dict:
        t_wall = _walltime.perf_counter()
        self.loop = SimEventLoop()
        asyncio.set_event_loop(self.loop)
        sim_clock = SimClock(self.loop)
        prev_clock = clock.install(sim_clock)
        import random as _random
        uid_rng = _random.Random(f"fed-uids:{self.seed}")
        token = lambda n: "%0*x" % (n * 2, uid_rng.getrandbits(n * 8))  # noqa: E731
        prev_sd_tokens = serverdir.set_token_source(token)
        prev_tr_tokens = trace_mod.set_token_source(token)
        prev_plan = chaos._PLAN
        plan = chaos.FaultPlan({"seed": self.seed, "rules": self.rules}) \
            if self.rules else None
        chaos.install_plan(plan)
        chaos.set_kill_handler(self.chaos_kill_handler)
        try:
            return self.loop.run_until_complete(
                asyncio.wait_for(self._main(scenario), timeout=self.horizon)
            )
        finally:
            chaos.set_kill_handler(None)
            chaos.install_plan(prev_plan)
            serverdir.set_token_source(prev_sd_tokens)
            trace_mod.set_token_source(prev_tr_tokens)
            clock.install(prev_clock)
            try:
                self._drain_loop()
            finally:
                try:
                    self.loop.close()
                finally:
                    asyncio.set_event_loop(None)
            self.wall_s = _walltime.perf_counter() - t_wall
            if self._own_dir:
                shutil.rmtree(self.root, ignore_errors=True)

    def _drain_loop(self) -> None:
        if self.loop is None or self.loop.is_closed():
            return
        self._stopping = True
        for shard in self.shards:
            if shard.server is not None:
                shard.kill_now()
        pending = [t for t in asyncio.all_tasks(self.loop) if not t.done()]
        for t in pending:
            t.cancel()
        if pending:
            try:
                self.loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True)
                )
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass

    async def _main(self, scenario) -> dict:
        self.shards = [
            _ShardSim(self, k) for k in range(self.shard_count)
        ]
        for shard in self.shards:
            await shard.start_server()
            self._supervisors.append(
                self.loop.create_task(shard.supervisor())
            )
            for _ in range(self.n_workers_per_shard):
                shard.add_worker()
        await scenario(self)
        await self.wait_all()
        await asyncio.sleep(max(self.heartbeat_secs, 2.0))
        makespan = clock.monotonic()
        audit = self._final_audit()
        self._stopping = True
        for t in self._supervisors:
            t.cancel()
        for shard in self.shards:
            shard.client.close()
            for worker in shard.workers.values():
                if not worker.dead:
                    worker.dead = True
                    if worker._task is not None:
                        worker._task.cancel()
                    if worker._link is not None:
                        worker._link.close()
        await asyncio.sleep(0.05)
        for shard in self.shards:
            server = shard.server
            if server is not None:
                if shard._event_tap_task is not None:
                    shard._event_tap_task.cancel()
                server._event_listeners.clear()
                await server.shutdown()
                shard.server = None
        if self.monitor.violations:
            raise InvariantViolation(self.monitor.violations[0])
        return {
            "seed": self.seed,
            "makespan": makespan,
            "shard_boots": [s.server_boots for s in self.shards],
            "driver_kills": self.driver_kills,
            "audit": audit,
            "violations": list(self.monitor.violations),
        }

    def _final_audit(self) -> dict:
        """Fleet-scoped quiescence audit.

        exactly-once: across ALL shard journals each (job, task) has at
        most one task-finished record (a migrated job's pre-move
        completions travel inside the migration-in record, never as
        re-emitted events, so cross-journal counting is sound).
        single ownership: each job is live on EXACTLY the shard the
        ownership log routes it to."""
        from hyperqueue_tpu.events.journal import Journal

        finished: dict[int, int] = {}
        terminal: set[int] = set()
        for shard in self.shards:
            if not shard.journal_path.exists():
                continue
            for record in Journal.read_all(shard.journal_path):
                kind = record.get("event")
                if kind not in ("task-finished", "task-failed",
                                "task-canceled"):
                    continue
                tid = (int(record["job"]) << 32) | int(record["task"])
                terminal.add(tid)
                if kind == "task-finished":
                    finished[tid] = finished.get(tid, 0) + 1
        dup = {t: n for t, n in finished.items() if n > 1}
        if dup:
            self.monitor._fail(
                f"cross-shard exactly-once violation: {len(dup)} task(s) "
                f"finished on more than one shard/incarnation, e.g. "
                f"{sorted(dup)[:5]}"
            )
        # migrated-in completions live inside migration-in records, not
        # as task events: credit the live servers' terminal counters too
        done_live: dict[int, int] = {}
        for shard in self.shards:
            server = shard.server
            if server is None:
                continue
            for job_id, job in server.jobs.jobs.items():
                c = job.counters
                done_live[job_id] = (
                    c["finished"] + c["failed"] + c["canceled"]
                )
        missing = 0
        for job_id, count in self.expected_tasks.items():
            done = sum(1 for t in terminal if (t >> 32) == job_id)
            done = max(done, done_live.get(job_id, 0))
            if done < count:
                missing += count - done
        if missing:
            self.monitor._fail(
                f"lost tasks: {missing} submitted task(s) never reached "
                f"a terminal state anywhere in the fleet"
            )
        try:
            omap = self.store().load()
        except OSError:
            omap = None
        owners_ok = 0
        for job_id in self.expected_tasks:
            owner = (
                omap.shard_for_job(job_id) if omap is not None
                else (job_id - 1) % self.shard_count
            )
            holders = [
                s.shard_id for s in self.shards
                if s.server is not None and job_id in s.server.jobs.jobs
            ]
            if holders != [owner]:
                self.monitor._fail(
                    f"ownership violation: job {job_id} is routed to "
                    f"shard {owner} but live on {holders}"
                )
            owners_ok += 1
        return {
            "tasks_terminal": len(terminal),
            "jobs_owned": owners_ok,
            "executions": len(self.monitor.exec_started),
            "events_seen": self.monitor.events_seen,
        }


def run_federated_scenario(scenario, **kwargs) -> dict:
    """One-call runner (tests use this)."""
    fed = FederatedSimulation(**kwargs)
    return fed.run(scenario)
