"""Simulation harness: the real Server under a virtual clock.

One :class:`Simulation` boots the production ``Server`` (reactor,
scheduler tick, journal + snapshot + restore, lazy store, autoalloc
controller) on a :class:`~hyperqueue_tpu.sim.loop.SimEventLoop`, wires
thousands of :class:`SimWorker`s and a :class:`SimClient` to it through
in-memory duplex streams, drives a synthetic workload at virtual arrival
times under a seeded :class:`FaultSchedule`, and checks invariants
continuously.  Single-threaded by construction: the PR 9/12 escape
hatches (``client_plane="reactor"``, ``journal_plane="reactor"``,
``fanout_senders=0``) plus ``solver_watchdog_timeout=0`` pin every plane
to the one virtual loop, so a run is a deterministic function of
(workload, seed, schedule).

Server kill -9 is modeled honestly in-process: the incarnation's event
tap is severed, the journal appender is abandoned with its unflushed
buffer discarded (``Journal.kill``), every server task and connection is
torn down abruptly, and a NEW ``Server`` object restores from the journal
file — driving the same restore/reattach/stream-replay choreography the
process-level chaos tests exercise, thousands of times faster.

Determinism contract: two runs with the same (workload, seed, schedule)
in the same interpreter produce bit-identical journal files and
decision-record streams.  Across interpreter invocations set
``PYTHONHASHSEED`` — a handful of str-set iterations in the server are
hash-order dependent.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import random
import shutil
import tempfile
import time as _walltime
from dataclasses import dataclass, field
from pathlib import Path

from hyperqueue_tpu.server.bootstrap import Server
from hyperqueue_tpu.sim.client import SimClient, SimSubmitStream
from hyperqueue_tpu.sim.faults import FaultSchedule
from hyperqueue_tpu.sim.invariants import InvariantMonitor, InvariantViolation
from hyperqueue_tpu.sim.loop import SimClock, SimEventLoop
from hyperqueue_tpu.sim.transport import duplex
from hyperqueue_tpu.sim.worker import SimWorker
from hyperqueue_tpu.sim.workloads import Workload
from hyperqueue_tpu.utils import chaos, clock, serverdir
from hyperqueue_tpu.utils import trace as trace_mod
from hyperqueue_tpu.utils.metrics import REGISTRY

logger = logging.getLogger("hq.sim")

# chunk size the harness streams arrays at (mirrors the CLI default)
CHUNK_SIZE = 16384


class SimKilled(asyncio.CancelledError):
    """Raised through a chaos action="kill" site to unwind the stack the
    way SIGKILL would: nothing after the injection point runs on the dead
    incarnation (and the task ends 'cancelled', never 'errored')."""


@dataclass
class SimResult:
    seed: int
    workload: str
    n_tasks: int
    makespan: float            # virtual seconds to quiescence
    wall_s: float              # real seconds the run took
    server_boots: int
    audit: dict
    decision_digest: str
    journal_digest: str
    decisions: list = field(repr=False, default_factory=list)
    violations: list = field(default_factory=list)
    # real per-tick scheduler latencies (ms), collected BEFORE decision
    # records are normalized (normalization strips wall timings)
    tick_ms: list = field(repr=False, default_factory=list)
    # PolicyState.stats() harvested at quiescence when the server ran
    # with a --policy-file (None on the flat objective)
    policy: dict | None = None
    # TickStats.shares() of the final incarnation — the per-phase half of
    # the PR 19 profile-blame summary (bench.py profile_summary)
    tick_shares: dict = field(repr=False, default_factory=dict)

    @property
    def virtual_tasks_per_wall_s(self) -> float:
        return self.n_tasks / self.wall_s if self.wall_s > 0 else 0.0


def _normalize_decision(record: dict) -> dict:
    """A decision record minus its perf_counter-measured fields (real CPU
    timings differ run-to-run by construction; everything semantic —
    virtual stamps included — must be bit-identical)."""
    out = {k: v for k, v in record.items()
           if k not in ("duration_ms", "phases")}
    solver = out.get("solver")
    if isinstance(solver, dict):
        out["solver"] = {
            k: v for k, v in solver.items()
            if k not in ("solve_ms", "inflight_ms", "dispatched_at_wall",
                         "mapped_at_wall")
        }
    return out


def _digest(obj) -> str:
    return hashlib.sha256(
        json.dumps(obj, sort_keys=True, default=repr).encode()
    ).hexdigest()


class Simulation:
    def __init__(
        self,
        workload: Workload,
        seed: int = 0,
        n_workers: int = 16,
        worker_cpus: int = 4,
        worker_groups: int = 1,
        faults: FaultSchedule | None = None,
        server_dir: Path | None = None,
        scheduler: str = "greedy-numpy",
        schedule_min_delay: float = 0.01,
        heartbeat_secs: float = 8.0,
        reattach_timeout: float = 5.0,
        restore_delay: float = 1.0,
        horizon: float | None = None,
        flight_ticks: int = 1 << 20,
        chunk_size: int = CHUNK_SIZE,
        server_kwargs: dict | None = None,
    ):
        self.workload = workload
        self.seed = seed
        self.n_workers = n_workers
        self.worker_cpus = worker_cpus
        self.worker_groups = max(worker_groups, 1)
        self.faults = faults or FaultSchedule(seed=seed, events=[])
        self.scheduler = scheduler
        self.schedule_min_delay = schedule_min_delay
        self.heartbeat_secs = heartbeat_secs
        self.reattach_timeout = reattach_timeout
        self.restore_delay = restore_delay
        # hard virtual deadline: a scenario that cannot quiesce inside it
        # is reported as a hang instead of spinning forever
        self.horizon = horizon or max(
            self.workload.horizon_hint * 4 + 3600.0, 3600.0
        )
        self.flight_ticks = flight_ticks
        self.chunk_size = max(int(chunk_size), 1)
        self.server_kwargs = dict(server_kwargs or {})

        self._own_dir = server_dir is None
        self.server_dir = Path(server_dir or tempfile.mkdtemp(
            prefix="hq-sim-"
        ))
        self.journal_path = self.server_dir / "journal.bin"

        self.loop: SimEventLoop | None = None
        self.monitor = InvariantMonitor(self)
        self.server: Server | None = None
        self.server_boots = 0
        self.workers: dict[str, SimWorker] = {}
        self.client = SimClient(self, "driver")
        self.expected_tasks: dict[int, int] = {}
        self._server_links: list = []
        self._server_down = None       # asyncio.Event, created in run()
        self._next_restore_delay = self.restore_delay
        self._stopping = False
        self._decisions: list[dict] = []
        self._tick_ms: list[float] = []
        self._event_tap_task = None
        self._fault_tasks: list = []
        self.wall_s = 0.0

    # --- connection points (SimWorker / SimClient call these) -----------
    def connect_worker(self, name: str):
        if self.server is None:
            raise ConnectionError("server is down")
        a, b = duplex(self.loop, name=f"w-{name}")
        self._server_links.append(a.link)
        self.server.accept_worker(b.reader, b.writer)
        return a

    def connect_client(self, name: str):
        if self.server is None:
            raise ConnectionError("server is down")
        a, b = duplex(self.loop, name=f"c-{name}")
        self._server_links.append(a.link)
        self.server.accept_client(b.reader, b.writer)
        return a

    # --- server lifecycle ------------------------------------------------
    async def start_server(self) -> Server:
        kwargs = dict(
            server_dir=self.server_dir,
            host="sim-host",
            disable_client_auth=True,
            disable_worker_auth=True,
            scheduler=self.scheduler,
            schedule_min_delay=self.schedule_min_delay,
            journal_path=self.journal_path,
            reattach_timeout=self.reattach_timeout,
            solver_watchdog_timeout=0.0,
            flight_recorder_ticks=self.flight_ticks,
            client_plane="reactor",
            journal_plane="reactor",
            fanout_senders=0,
            memory_transport=True,
        )
        kwargs.update(self.server_kwargs)
        server = Server(**kwargs)
        await server.start()
        self.server = server
        self.server_boots += 1
        self._server_links = []
        # tap the journaled event stream into the invariant monitor
        tap: asyncio.Queue = asyncio.Queue()
        server._event_listeners.append(tap)
        self._event_tap_task = self.loop.create_task(self._drain_tap(tap))
        if server.n_boots > 1:
            # a restore: every pre-crash promise must hold on this
            # incarnation (ack-implies-durable)
            self.monitor.check_restored_server(server)
        return server

    async def _drain_tap(self, tap: asyncio.Queue) -> None:
        while True:
            record = await tap.get()
            self.monitor.on_event(record)

    def _collect_decisions(self, server: Server) -> None:
        for r in server.core.flight.ticks():
            dur = r.get("duration_ms")
            if isinstance(dur, (int, float)):
                self._tick_ms.append(float(dur))
            self._decisions.append(_normalize_decision(r))

    def _kill_server_now(self) -> None:
        """kill -9 the current incarnation, synchronously: everything
        after this instant is lost exactly as with a process SIGKILL."""
        server = self.server
        if server is None:
            return
        self.server = None
        self._collect_decisions(server)
        # sever visibility first: nothing the dying incarnation does past
        # this point may reach the monitor, subscribers, or the journal
        server._event_listeners.clear()
        server._subscribers.clear()
        if self._event_tap_task is not None:
            self._event_tap_task.cancel()
            self._event_tap_task = None
        if server.journal is not None:
            server.journal.kill()   # unflushed tail is LOST
            server.journal = None
        server.jplane = None
        for t in (list(server._tasks) + list(server._client_tasks)
                  + list(server._conn_tasks)):
            t.cancel()
        if server.autoalloc is not None:
            server.autoalloc.stop()
        if server._metrics_hook is not None:
            REGISTRY.remove_collect_hook(server._metrics_hook)
            server._metrics_hook = None
        for link in self._server_links:
            link.abort()
        self._server_links = []
        if self._server_down is not None:
            self._server_down.set()
        logger.info("sim: server killed at t=%.3f", clock.monotonic())

    def chaos_kill_handler(self) -> None:
        """utils/chaos action="kill" in-process: kill the server NOW and
        unwind the injecting call stack (a real SIGKILL never returns)."""
        self._kill_server_now()
        raise SimKilled("chaos kill")

    async def kill_server(self, restore_after: float | None = None) -> None:
        self._next_restore_delay = (
            restore_after if restore_after is not None else self.restore_delay
        )
        self._kill_server_now()
        await asyncio.sleep(0)

    async def _server_supervisor(self) -> None:
        """Restore a killed server after the configured delay — the
        operator/systemd half of the crash choreography."""
        while True:
            await self._server_down.wait()
            self._server_down.clear()
            if self._stopping:
                return
            await asyncio.sleep(self._next_restore_delay)
            self._next_restore_delay = self.restore_delay
            if self._stopping:
                return
            await self.start_server()
            logger.info("sim: server restored at t=%.3f", clock.monotonic())

    # --- workers ----------------------------------------------------------
    def add_worker(self, name: str | None = None, **kwargs) -> SimWorker:
        name = name or f"w{len(self.workers)}"
        group = kwargs.pop(
            "group", f"g{len(self.workers) % self.worker_groups}"
        )
        worker = SimWorker(
            self, name,
            n_cpus=kwargs.pop("n_cpus", self.worker_cpus),
            group=group,
            heartbeat_secs=kwargs.pop("heartbeat_secs", self.heartbeat_secs),
            **kwargs,
        )
        self.workers[name] = worker
        worker.start()
        return worker

    # --- fault driver ----------------------------------------------------
    async def _drive_faults(self) -> None:
        for event in self.faults.events:
            if event.kind == "chaos_rule":
                continue  # pre-installed as at_t rules (see run())
            delay = event.at - clock.monotonic()
            if delay > 0:
                await asyncio.sleep(delay)
            # apply concurrently: a 30 s partition window must not push
            # every later fault 30 s off its scheduled instant
            t = self.loop.create_task(self._apply_fault(event))
            self._fault_tasks.append(t)

    async def _apply_fault(self, event) -> None:
        logger.info("sim fault: %s", event.describe())
        if event.kind == "server_kill":
            await self.kill_server(restore_after=event.delay)
            return
        if event.kind == "clock_skew":
            clock.get().skew += event.delta
            return
        worker = self.workers.get(event.target)
        if worker is None or worker.dead:
            return
        if event.kind == "worker_kill":
            worker.kill()
            if event.delay >= 0:
                await asyncio.sleep(event.delay)
                if not self._stopping:
                    worker.revive()
        elif event.kind == "partition":
            worker.partition(True)
            await asyncio.sleep(event.duration)
            worker.partition(False)
        elif event.kind == "straggler":
            worker.speed = event.factor
            await asyncio.sleep(event.duration)
            worker.speed = 1.0
        else:
            raise ValueError(f"unknown fault kind {event.kind!r}")

    def _chaos_plan(self) -> chaos.FaultPlan | None:
        """One FaultPlan holding every chaos_rule event as a
        schedule-driven (at_t-gated) rule."""
        rules = []
        epoch = clock.get().epoch
        for event in self.faults.events:
            if event.kind != "chaos_rule":
                continue
            rule = dict(event.rule)
            rule.setdefault("at_t", epoch + event.at)
            rules.append(rule)
        if not rules:
            return None
        return chaos.FaultPlan({"seed": self.seed, "rules": rules})

    # --- workload driver -------------------------------------------------
    async def _drive_workload(self) -> None:
        submits = sorted(
            enumerate(self.workload.submits), key=lambda p: (p[1].at, p[0])
        )
        for i, spec in submits:
            delay = spec.at - clock.monotonic()
            if delay > 0:
                await asyncio.sleep(delay)
            await self._submit_spec(i, spec)

    async def _submit_spec(self, i: int, spec) -> None:
        """Exactly-once submission through the chunked-stream plane: a
        submit whose ack was lost to a crash replays by (uid, index)
        instead of duplicating the job."""
        desc = spec.job_desc
        header = {k: v for k, v in desc.items()
                  if k not in ("array", "tasks")}
        stream = SimSubmitStream(self.client, uid=f"sim-{self.seed}-{i}",
                                 header=header)
        array = desc.get("array")
        if array is not None and array.get("id_range") and \
                not array.get("entries"):
            lo, hi = array["id_range"]
            cursors = list(range(lo, hi, self.chunk_size))
            for j, start in enumerate(cursors):
                chunk = dict(array)
                chunk["id_range"] = [start, min(start + self.chunk_size, hi)]
                await stream.send_chunk(
                    array=chunk, last=(j == len(cursors) - 1)
                )
        elif array is not None:
            await stream.send_chunk(array=array, last=True)
        else:
            await stream.send_chunk(tasks=desc.get("tasks") or [],
                                    last=True)
        job_id = stream.job_id
        self.expected_tasks[job_id] = (
            self.expected_tasks.get(job_id, 0) + spec.n_tasks
        )

    # --- drain helper (scenario surface) ---------------------------------
    async def drain_worker(self, worker: SimWorker,
                           timeout: float = 60.0) -> None:
        """Gracefully drain one worker through the real RPC, recording the
        drain instant for the no-new-assignments invariant."""
        wid = worker.worker_id
        self.monitor.on_drain_started(wid, clock.monotonic())
        await self.client.worker_stop([wid], drain=True, timeout=timeout)

    # --- main -------------------------------------------------------------
    def run(self) -> SimResult:
        """Build the loop, run the scenario to quiescence, audit, tear
        down.  Synchronous wrapper — the whole simulation lives inside."""
        t_wall = _walltime.perf_counter()
        self.loop = SimEventLoop()
        asyncio.set_event_loop(self.loop)
        sim_clock = SimClock(self.loop)
        prev_clock = clock.install(sim_clock)
        uid_rng = random.Random(f"uids:{self.seed}")
        token = lambda n: "%0*x" % (n * 2, uid_rng.getrandbits(n * 8))  # noqa: E731
        prev_sd_tokens = serverdir.set_token_source(token)
        prev_tr_tokens = trace_mod.set_token_source(token)
        prev_plan = chaos._PLAN
        chaos.install_plan(self._chaos_plan())
        chaos.set_kill_handler(self.chaos_kill_handler)
        result = None
        try:
            result = self.loop.run_until_complete(
                asyncio.wait_for(self._main(), timeout=self.horizon)
            )
            return result
        finally:
            chaos.set_kill_handler(None)
            chaos.install_plan(prev_plan)
            serverdir.set_token_source(prev_sd_tokens)
            trace_mod.set_token_source(prev_tr_tokens)
            clock.install(prev_clock)
            try:
                self._drain_loop()
            finally:
                try:
                    self.loop.close()
                finally:
                    asyncio.set_event_loop(None)
            self.wall_s = _walltime.perf_counter() - t_wall
            if result is not None:
                result.wall_s = self.wall_s
            if self._own_dir:
                shutil.rmtree(self.server_dir, ignore_errors=True)

    def _drain_loop(self) -> None:
        """Unwind every pending task inside the loop before closing it:
        an abandoned scenario (timeout, violation) must not leak tasks
        whose finalizers would run against a closed loop at GC time."""
        if self.loop is None or self.loop.is_closed():
            return
        self._stopping = True
        if self.server is not None:
            self._kill_server_now()
        pending = [
            t for t in asyncio.all_tasks(self.loop) if not t.done()
        ]
        for t in pending:
            t.cancel()
        if pending:
            try:
                self.loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True)
                )
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass

    async def _main(self) -> SimResult:
        self._server_down = asyncio.Event()
        await self.start_server()
        supervisor = self.loop.create_task(self._server_supervisor())
        for i in range(self.n_workers):
            self.add_worker()
        fault_task = self.loop.create_task(self._drive_faults())
        await self._drive_workload()
        # quiesce: every submitted job's tasks terminal
        await self.client.job_wait(sorted(self.expected_tasks))
        # let trailing uplinks/events/retries settle, then a clean stop
        await asyncio.sleep(max(self.heartbeat_secs, 2.0))
        makespan = clock.monotonic()
        self._stopping = True
        fault_task.cancel()
        for t in self._fault_tasks:
            t.cancel()
        supervisor.cancel()
        self.client.close()
        for worker in self.workers.values():
            if not worker.dead:
                worker.dead = True
                if worker._task is not None:
                    worker._task.cancel()
                if worker._link is not None:
                    worker._link.close()
        # let the closed worker links unwind their connection handlers
        # (worker-lost events journal BEFORE the journal closes below)
        await asyncio.sleep(0.05)
        server = self.server
        audit = {}
        policy_stats = None
        tick_shares = {}
        if server is not None:
            self._collect_decisions(server)
            if server.core.policy is not None:
                policy_stats = server.core.policy.stats()
            try:
                tick_shares = server.core.tick_stats.shares()
            except Exception:  # noqa: BLE001 - telemetry only
                tick_shares = {}
            if self._event_tap_task is not None:
                self._event_tap_task.cancel()
            server._event_listeners.clear()
            await server.shutdown()
            self.server = None
        # violations raised inside loop CALLBACKS (worker timers) land in
        # the loop's exception handler, not here — the recorded list is
        # the reliable channel, so re-raise the first one now
        if self.monitor.violations:
            raise InvariantViolation(self.monitor.violations[0])
        audit = self.monitor.final_check(
            self.journal_path, self.expected_tasks,
            expect_failed=self.workload.expect_failed,
        )
        journal_digest = hashlib.sha256(
            self.journal_path.read_bytes()
        ).hexdigest()
        return SimResult(
            seed=self.seed,
            workload=self.workload.name,
            n_tasks=self.workload.n_tasks,
            makespan=makespan,
            wall_s=0.0,  # stamped by run()'s caller via wall_s attr
            server_boots=self.server_boots,
            audit=audit,
            decision_digest=_digest(self._decisions),
            journal_digest=journal_digest,
            decisions=self._decisions,
            violations=list(self.monitor.violations),
            tick_ms=self._tick_ms,
            policy=policy_stats,
            tick_shares=tick_shares,
        )


def run_scenario(
    workload: Workload,
    seed: int = 0,
    n_workers: int = 16,
    faults: FaultSchedule | None = None,
    **kwargs,
) -> SimResult:
    """One-call scenario runner (the CLI and tests use this)."""
    sim = Simulation(
        workload, seed=seed, n_workers=n_workers, faults=faults, **kwargs
    )
    return sim.run()


def bisect_failure(
    make_sim,
    faults: FaultSchedule,
) -> tuple[int, list[str]]:
    """Shrink a failing schedule to its minimal failing prefix.

    ``make_sim(schedule) -> Simulation``; returns (k, descriptions of the
    minimal prefix).  Runs O(log n) full simulations."""
    from hyperqueue_tpu.sim.faults import bisect_minimal_prefix
    from hyperqueue_tpu.sim.loop import SimDeadlockError

    def fails(k: int) -> bool:
        sim = make_sim(faults.prefix(k))
        try:
            sim.run()
            return False
        except (InvariantViolation, SimDeadlockError, asyncio.TimeoutError):
            return True

    k = bisect_minimal_prefix(fails, len(faults))
    return k, faults.prefix(k).describe()
