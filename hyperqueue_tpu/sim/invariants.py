"""Always-on invariant checking over the simulation's event streams.

The monitor consumes three ground-truth streams and cross-checks them
continuously — a violation raises at the moment the history first proves
it, not at the end of the run:

- the server's journaled event stream (every incarnation's live
  ``emit_event`` deliveries, tapped via a listener queue);
- worker-side execution truth (SimWorkers report every execution start,
  finish, and loss — what actually "ran", independent of what the server
  believes);
- client-side acknowledgements (submit and chunk acks — what the client
  was promised).

Invariant catalog (docs/simulation.md has the prose version):

``exactly-once execution``
    No (task, instance) ever starts executing twice — the server never
    double-spawns an incarnation and workers dedup replayed computes.
    (Distinct instances of one task may overlap transiently under
    partition — that is by design; instance fencing picks one winner.)
``fence monotonicity``
    Per task, the instance ids in started executions and task-started
    events never decrease; a re-execution always carries a newer (or, for
    a reattach, the same) instance.
``drain-means-no-new-assignments``
    After a drain begins for a worker id, no compute message reaches that
    worker at any later virtual instant.
``ack-implies-durable``
    Every chunk acked to the client is present (stream uid + chunk index
    applied) on every later server incarnation — checked at each restore.
``no lost tasks`` / ``exactly-once acceptance`` (final)
    At quiescence the journal contains exactly one terminal record per
    submitted task, and every acked submit's tasks are accounted for.
``gang atomicity``
    A multi-node task never starts with fewer workers than its requested
    ``n_nodes`` (no gang member ever starts without its siblings), and no
    worker is a member of two concurrently-running gangs.  Checked on
    every task-started event against the live server's request map; holds
    identically for the host reservation drain and the fused in-solve
    gang rows (``--scheduler greedy-fused``).
"""

from __future__ import annotations

import logging

logger = logging.getLogger("hq.sim.invariants")


class InvariantViolation(AssertionError):
    """A robustness property the simulated history disproves."""


class InvariantMonitor:
    def __init__(self, sim):
        self.sim = sim
        self.violations: list[str] = []
        # (task_id, instance) -> (worker name, t) of the execution start
        self.exec_started: dict[tuple[int, int], tuple[str, float]] = {}
        self.exec_finished: dict[tuple[int, int], float] = {}
        self.exec_lost: set[tuple[int, int]] = set()
        # task_id -> highest instance ever seen starting
        self.max_instance: dict[int, int] = {}
        # worker id -> virtual time its drain began
        self.drain_started: dict[int, float] = {}
        # client promises
        self.acked_jobs: dict[int, int] = {}          # job -> n_tasks acked
        self.acked_chunks: dict[str, set[int]] = {}   # uid -> chunk indexes
        self.chunk_jobs: dict[str, int] = {}
        # journal-event observations (across incarnations)
        self.started_events = 0
        self.finished_events = 0
        self.events_seen = 0
        # task_id -> worker-id member set of each currently-running gang
        self.gang_active: dict[int, set[int]] = {}
        self.gang_starts = 0

    # --- plumbing -------------------------------------------------------
    def _fail(self, message: str) -> None:
        self.violations.append(message)
        logger.error("INVARIANT VIOLATION: %s", message)
        raise InvariantViolation(message)

    # --- worker-side truth ---------------------------------------------
    def on_worker_session(self, name: str, worker_id: int, t: float) -> None:
        pass  # bookkeeping hook (kept for scenario assertions)

    def on_compute_delivered(self, name: str, worker_id: int, task_id: int,
                             instance: int, t: float) -> None:
        started = self.drain_started.get(worker_id)
        if started is not None and t > started:
            self._fail(
                f"drain violation: worker {worker_id} ({name}) received "
                f"compute for task {task_id} at t={t:.3f}, "
                f"{t - started:.3f}s after its drain began"
            )

    def on_exec_started(self, name: str, worker_id: int, task_id: int,
                        instance: int, t: float) -> None:
        key = (task_id, instance)
        prior = self.exec_started.get(key)
        if prior is not None:
            self._fail(
                f"double spawn: (task {task_id}, instance {instance}) "
                f"started on {name} at t={t:.3f} but already started on "
                f"{prior[0]} at t={prior[1]:.3f}"
            )
        last = self.max_instance.get(task_id)
        if last is not None and instance < last:
            self._fail(
                f"fence regression: task {task_id} started instance "
                f"{instance} after instance {last} had already started"
            )
        self.max_instance[task_id] = instance
        self.exec_started[key] = (name, t)

    def on_exec_finished(self, name: str, worker_id: int, task_id: int,
                         instance: int, t: float, failed: bool) -> None:
        key = (task_id, instance)
        if key in self.exec_finished:
            self._fail(
                f"double completion: (task {task_id}, instance {instance}) "
                f"finished twice on {name}"
            )
        self.exec_finished[key] = t

    def on_exec_lost(self, name: str, worker_id: int, task_id: int,
                     instance: int, t: float, reason: str) -> None:
        self.exec_lost.add((task_id, instance))

    def on_drain_started(self, worker_id: int, t: float) -> None:
        self.drain_started[worker_id] = t

    # --- client promises -------------------------------------------------
    def on_submit_ack(self, job_id: int, n_tasks: int) -> None:
        self.acked_jobs[job_id] = self.acked_jobs.get(job_id, 0) + n_tasks

    def on_chunk_ack(self, uid: str, job_id: int, index: int, n_tasks: int,
                     dup: bool) -> None:
        self.acked_chunks.setdefault(uid, set()).add(index)
        self.chunk_jobs[uid] = job_id

    # --- journal events (live tap) ---------------------------------------
    def on_event(self, record: dict) -> None:
        self.events_seen += 1
        kind = record.get("event")
        if kind == "task-started":
            self.started_events += 1
            task = record.get("task")
            job = record.get("job")
            instance = record.get("instance", 0)
            if task is not None and job is not None:
                tid = (int(job) << 32) | int(task)
                last = self.max_instance.get(tid)
                if last is not None and instance < last:
                    self._fail(
                        f"fence regression in event stream: task "
                        f"{job}@{task} announced instance {instance} after "
                        f"{last}"
                    )
                self._check_gang_start(tid, record)
        elif kind in ("task-finished", "task-failed", "task-canceled"):
            if kind == "task-finished":
                self.finished_events += 1
            task = record.get("task")
            job = record.get("job")
            if task is not None and job is not None:
                self.gang_active.pop((int(job) << 32) | int(task), None)

    def _check_gang_start(self, tid: int, record: dict) -> None:
        """Gang atomicity: a multi-node start must carry exactly n_nodes
        workers, none of which belongs to another running gang."""
        server = self.sim.server
        if server is None:
            return  # event from a just-killed incarnation; nothing to read
        task = server.core.tasks.get(tid)
        if task is None:
            return
        rqv = server.core.rq_map.get_variants(task.rq_id)
        variant = int(record.get("variant", 0) or 0)
        if variant >= len(rqv.variants):
            return
        n_nodes = rqv.variants[variant].n_nodes
        if not n_nodes:
            return
        members = set(record.get("workers") or ())
        t = record.get("time", 0.0)
        if len(members) != n_nodes:
            self._fail(
                f"gang atomicity violation: task {tid} (n_nodes={n_nodes}) "
                f"started with {len(members)} worker(s) "
                f"{sorted(members)} at t={t}"
            )
        for other_tid, other_members in self.gang_active.items():
            if other_tid == tid:
                continue  # a restart supersedes the prior instance
            overlap = members & other_members
            if overlap:
                self._fail(
                    f"gang overlap violation: workers {sorted(overlap)} "
                    f"belong to running gang {other_tid} but gang {tid} "
                    f"started on them at t={t}"
                )
        self.gang_active[tid] = members
        self.gang_starts += 1

    # --- restore-time checks ---------------------------------------------
    def check_restored_server(self, server) -> None:
        """Every promise acked before the crash must hold on the restored
        incarnation: acked chunk streams present with their applied
        indexes, acked jobs known."""
        for uid, indexes in self.acked_chunks.items():
            job_id = self.chunk_jobs.get(uid)
            job = server.jobs.jobs.get(job_id)
            if job is None:
                self._fail(
                    f"ack-durability violation: job {job_id} (stream "
                    f"{uid}) was acked but is unknown after restore"
                )
            stream = job.streams.get(uid)
            applied = stream["applied"] if stream else set()
            missing = indexes - set(applied)
            # a sealed stream's applied set is released at job
            # termination; a terminal job accounts for everything
            if missing and not job.is_terminated():
                self._fail(
                    f"ack-durability violation: stream {uid} chunks "
                    f"{sorted(missing)} were acked but not applied after "
                    f"restore"
                )
        for job_id in self.acked_jobs:
            if job_id not in server.jobs.jobs:
                self._fail(
                    f"ack-durability violation: job {job_id} was acked "
                    f"but is unknown after restore"
                )

    # --- final audit ------------------------------------------------------
    def final_check(self, journal_path, expected_tasks: dict[int, int],
                    expect_failed: int = 0) -> dict:
        """Quiescent-state audit straight from the journal file.

        ``expected_tasks``: job id -> task count that must have reached a
        terminal state exactly once.  Returns summary counts."""
        from hyperqueue_tpu.events.journal import Journal

        finished: dict[int, int] = {}
        failed: dict[int, int] = {}
        canceled: dict[int, int] = {}
        submitted: dict[int, set] = {}
        for record in Journal.read_all(journal_path):
            kind = record.get("event")
            job = record.get("job")
            task = record.get("task")
            if kind == "task-finished":
                tid = (int(job) << 32) | int(task)
                finished[tid] = finished.get(tid, 0) + 1
            elif kind == "task-failed":
                tid = (int(job) << 32) | int(task)
                failed[tid] = failed.get(tid, 0) + 1
            elif kind == "task-canceled":
                tid = (int(job) << 32) | int(task)
                canceled[tid] = canceled.get(tid, 0) + 1
        dup_finished = {t: n for t, n in finished.items() if n > 1}
        if dup_finished:
            self._fail(
                f"exactly-once violation: {len(dup_finished)} task(s) have "
                f"multiple task-finished journal records, e.g. "
                f"{sorted(dup_finished)[:5]}"
            )
        terminal = set(finished) | set(failed) | set(canceled)
        missing_total = 0
        for job_id, count in expected_tasks.items():
            done = sum(1 for t in terminal if (t >> 32) == job_id)
            if done < count:
                missing_total += count - done
        if missing_total:
            self._fail(
                f"lost tasks: {missing_total} submitted task(s) never "
                f"reached a terminal state in the journal"
            )
        n_failed = len(failed)
        if n_failed != expect_failed:
            self._fail(
                f"unexpected failures: {n_failed} task(s) failed "
                f"(expected {expect_failed})"
            )
        return {
            "finished": len(finished),
            "failed": n_failed,
            "canceled": len(canceled),
            "events_seen": self.events_seen,
            "executions": len(self.exec_started),
            "gang_starts": self.gang_starts,
        }
