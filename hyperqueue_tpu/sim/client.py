"""SimClient: the simulator's client, over the real client plane.

Requests go through the in-loop client plane (``--client-plane reactor``)
via an in-memory stream pair, so auth, framing, the durability-gated reply
path and every ``_client_*`` handler run unchanged.  The client survives
server death the way a retrying CLI does: a request that dies with the
connection is retried against the next server incarnation.

:class:`SimSubmitStream` mirrors ``client/connection.py``'s chunked
submit contract at window 1: chunks are keyed (uid, index), the job id is
pinned by the first ack, and after a reconnect every unacked chunk is
replayed — the server's applied-index journaling turns the replay into
idempotent duplicate acks.  This is what the kill -9 mid-chunked-submit
re-enactment drives.
"""

from __future__ import annotations

import asyncio
import logging

from hyperqueue_tpu.transport.auth import (
    ROLE_CLIENT,
    ROLE_SERVER,
    AuthError,
    do_authentication,
)

logger = logging.getLogger("hq.sim.client")


class SimClientError(RuntimeError):
    pass


class SimClient:
    def __init__(self, sim, name: str = "client"):
        self.sim = sim
        self.name = name
        self._conn = None
        self._link = None
        self._lock = asyncio.Lock()

    async def _ensure_conn(self):
        if self._conn is not None and self._link is not None \
                and self._link.alive:
            return self._conn
        endpoint = self.sim.connect_client(self.name)
        self._link = endpoint.link
        self._conn = await do_authentication(
            endpoint.reader, endpoint.writer, ROLE_CLIENT, ROLE_SERVER, None
        )
        return self._conn

    def drop_connection(self) -> None:
        if self._link is not None:
            self._link.close()
        self._conn = None
        self._link = None

    async def request(self, msg: dict, retries: int = 50,
                      retry_delay: float = 0.25) -> dict:
        """One request/response exchange; a connection that dies
        mid-exchange is retried against the (next) server.  NOT safe for
        non-idempotent ops across a crash — chunked streams exist for
        exactly-once submission."""
        async with self._lock:
            last: Exception | None = None
            for _ in range(retries):
                try:
                    conn = await self._ensure_conn()
                    await conn.send(msg)
                    reply = await conn.recv()
                    if reply.get("op") == "error":
                        raise SimClientError(reply.get("message", "error"))
                    return reply
                except (ConnectionError, OSError, AuthError,
                        asyncio.IncompleteReadError) as e:
                    last = e
                    self.drop_connection()
                    await asyncio.sleep(retry_delay)
            raise SimClientError(f"request failed after retries: {last}")

    # --- convenience wrappers ------------------------------------------
    async def submit(self, job_desc: dict) -> dict:
        reply = await self.request({"op": "submit", "job": job_desc})
        self.sim.monitor.on_submit_ack(
            reply["job_id"], reply.get("n_tasks", 0)
        )
        return reply

    async def job_wait(self, job_ids: list[int]) -> dict:
        return await self.request({"op": "job_wait", "job_ids": job_ids},
                                  retries=200)

    async def job_info(self, job_ids: list[int]) -> dict:
        return await self.request({"op": "job_info", "job_ids": job_ids})

    async def job_list(self) -> dict:
        return await self.request({"op": "job_list"})

    async def worker_stop(self, worker_ids: list[int], drain: bool = False,
                          timeout: float | None = None) -> dict:
        msg: dict = {"op": "worker_stop", "worker_ids": worker_ids}
        if drain:
            msg["drain"] = True
            if timeout is not None:
                msg["timeout"] = timeout
        return await self.request(msg)

    def close(self) -> None:
        self.drop_connection()


class SimSubmitStream:
    """Chunked exactly-once submit, window 1, with crash replay."""

    def __init__(self, client: SimClient, uid: str, header: dict):
        self.client = client
        self.uid = uid
        self.header = dict(header)
        self.job_id: int | None = None
        self.n_tasks = 0
        self.acked: set[int] = set()
        self._next_index = 0

    async def send_chunk(self, array: dict | None = None,
                         tasks: list | None = None,
                         last: bool = False) -> dict:
        index = self._next_index
        self._next_index += 1
        msg: dict = {"op": "submit_chunk", "uid": self.uid, "i": index,
                     "job": dict(self.header)}
        if self.job_id is not None:
            msg["job"]["job_id"] = self.job_id
        if array is not None:
            msg["array"] = array
        if tasks is not None:
            msg["tasks"] = tasks
        if last:
            msg["last"] = True
        reply = await self._send_until_acked(msg)
        return reply

    async def _send_until_acked(self, msg: dict) -> dict:
        client = self.client
        while True:
            try:
                async with client._lock:
                    conn = await client._ensure_conn()
                    # job id may have been pinned by a replayed chunk
                    if self.job_id is not None:
                        msg["job"]["job_id"] = self.job_id
                    await conn.send(msg)
                    reply = await conn.recv()
            except (ConnectionError, OSError, AuthError,
                    asyncio.IncompleteReadError):
                client.drop_connection()
                await asyncio.sleep(0.25)
                continue  # replay the SAME (uid, index): idempotent
            if reply.get("op") == "error":
                raise SimClientError(reply.get("message", "chunk rejected"))
            self.job_id = reply["job_id"]
            index = reply["i"]
            if index not in self.acked:
                self.acked.add(index)
                if not reply.get("dup"):
                    self.n_tasks += reply.get("n_tasks", 0)
                self.client.sim.monitor.on_chunk_ack(
                    self.uid, self.job_id, index, reply.get("n_tasks", 0),
                    dup=bool(reply.get("dup")),
                )
            return reply
