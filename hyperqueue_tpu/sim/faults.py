"""Seeded fault schedules: reproducible chaos for the simulator.

A :class:`FaultSchedule` is an explicit, ordered list of
:class:`FaultEvent`s pinned to virtual times — the unit the repro/bisect
loop shrinks.  :meth:`FaultSchedule.generate` derives one from a seed
(same seed, same schedule, always), mixing the sim-native faults:

- ``worker_kill`` (+ optional revive delay) — unclean worker death; its
  running executions are lost and the server requeues with crash
  accounting;
- ``server_kill`` (+ restore delay) — in-process kill -9: the server's
  in-memory state is dropped, the unflushed journal tail is lost, and a
  new incarnation restores from the journal (workers reattach, streams
  replay);
- ``partition`` — a worker's link drops everything for a duration while
  both sides think it is up (heartbeat reaping territory);
- ``straggler`` — a worker runs N× slower for a duration;
- ``clock_skew`` — step the wall clock by delta seconds (monotonic time
  is unaffected, like a stepped NTP correction);
- ``chaos_rule`` — install a message-plane rule through the existing
  ``utils/chaos.py`` FaultPlan surface (drop/dup/delay at
  server.send/server.recv, raise at solve, kill at server.event — the
  same sites the process-level chaos tests use).

The driver applies events in time order on the virtual clock; everything
is deterministic because the schedule is data, not dice rolled at fire
time.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field


@dataclass(frozen=True)
class FaultEvent:
    at: float                 # virtual monotonic time
    kind: str                 # see module docstring
    target: str = ""          # worker name ("" = server / global)
    duration: float = 0.0     # partition / straggler window
    factor: float = 1.0       # straggler slowdown
    delay: float = 1.0        # revive/restore delay for kills
    delta: float = 0.0        # clock skew step
    rule: dict | None = None  # chaos_rule payload

    def describe(self) -> str:
        bits = [f"t={self.at:g}", self.kind]
        if self.target:
            bits.append(self.target)
        if self.kind in ("partition", "straggler"):
            bits.append(f"for {self.duration:g}s")
        if self.kind == "straggler":
            bits.append(f"x{self.factor:g}")
        if self.kind == "server_kill":
            bits.append(f"restore after {self.delay:g}s")
        if self.kind == "chaos_rule":
            bits.append(repr(self.rule))
        return " ".join(bits)


@dataclass
class FaultSchedule:
    seed: int = 0
    events: list[FaultEvent] = field(default_factory=list)

    def __post_init__(self) -> None:
        self.events.sort(key=lambda e: e.at)

    def __len__(self) -> int:
        return len(self.events)

    def prefix(self, n: int) -> "FaultSchedule":
        return FaultSchedule(seed=self.seed, events=self.events[:n])

    def describe(self) -> list[str]:
        return [e.describe() for e in self.events]

    @classmethod
    def generate(
        cls,
        seed: int,
        horizon: float,
        worker_names: list[str],
        *,
        rate: float = 0.02,
        server_kills: int = 1,
        partitions: bool = True,
        stragglers: bool = True,
        clock_skew: bool = True,
        message_faults: bool = True,
    ) -> "FaultSchedule":
        """A seeded schedule over ``[horizon*0.05, horizon*0.8)``.

        ``rate`` is faults per worker-second (in expectation) for the
        worker-scoped faults; server kills are scheduled explicitly so a
        run always exercises restore when asked to."""
        rng = random.Random(f"faultgen:{seed}")
        lo, hi = horizon * 0.05, horizon * 0.8
        events: list[FaultEvent] = []
        n_worker_faults = max(int(rate * len(worker_names) * (hi - lo)), 1)
        kinds = ["worker_kill"]
        if partitions:
            kinds.append("partition")
        if stragglers:
            kinds.append("straggler")
        for _ in range(n_worker_faults):
            kind = rng.choice(kinds)
            target = rng.choice(worker_names)
            at = rng.uniform(lo, hi)
            if kind == "worker_kill":
                events.append(FaultEvent(
                    at=at, kind=kind, target=target,
                    delay=rng.uniform(0.5, 5.0),
                ))
            elif kind == "partition":
                events.append(FaultEvent(
                    at=at, kind=kind, target=target,
                    duration=rng.uniform(1.0, 30.0),
                ))
            else:
                events.append(FaultEvent(
                    at=at, kind=kind, target=target,
                    duration=rng.uniform(5.0, 60.0),
                    factor=rng.uniform(2.0, 16.0),
                ))
        for _ in range(server_kills):
            events.append(FaultEvent(
                at=rng.uniform(lo, hi), kind="server_kill",
                delay=rng.uniform(0.5, 3.0),
            ))
        if clock_skew:
            events.append(FaultEvent(
                at=rng.uniform(lo, hi), kind="clock_skew",
                delta=rng.uniform(-30.0, 30.0),
            ))
        if message_faults:
            # schedule-driven chaos rules (utils/chaos.py at_t triggers):
            # deterministic regardless of message arrival interleaving.
            # Only RECOVERABLE actions (dup exercises dedup/idempotency,
            # delay exercises reordering tolerance): a dropped message on
            # a connection that stays up has no recovery path in the real
            # system either — TCP does not lose frames mid-connection, so
            # message loss is only ever modeled together with a
            # connection loss (worker_kill/partition above).
            for site, op in (("server.recv", "task_finished"),
                             ("server.send", "compute")):
                if rng.random() < 0.75:
                    events.append(FaultEvent(
                        at=rng.uniform(lo, hi), kind="chaos_rule",
                        rule={
                            "site": site, "op": op,
                            "action": rng.choice(["dup", "delay"]),
                            "times": rng.randint(1, 3),
                        },
                    ))
        return cls(seed=seed, events=events)


def bisect_minimal_prefix(run_prefix, n_events: int) -> int:
    """Smallest k such that ``run_prefix(k)`` still fails.

    ``run_prefix(k) -> bool`` replays the scenario with only the first k
    fault events and returns True when the violation reproduces.  Assumes
    prefix-monotonicity (the standard delta-debugging assumption: faults
    after the triggering one are noise); the returned k is verified by
    construction since the binary search only narrows on observed
    failures."""
    lo, hi = 0, n_events  # invariant: prefix(hi) fails, prefix(lo-1)… unknown
    while lo < hi:
        mid = (lo + hi) // 2
        if run_prefix(mid):
            hi = mid
        else:
            lo = mid + 1
    return hi
