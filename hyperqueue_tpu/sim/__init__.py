"""Deterministic cluster simulation (ISSUE 14).

Runs the REAL server — reactor, scheduler tick, journal + snapshot +
restore, lazy store, autoalloc — on a virtual-clock event loop with
simulated workers and clients over in-memory transports, under seeded
fault schedules, with always-on invariant checking.  See
``docs/simulation.md`` and ``python -m hyperqueue_tpu.sim --help``.
"""

from hyperqueue_tpu.sim.faults import FaultEvent, FaultSchedule
from hyperqueue_tpu.sim.harness import (
    SimResult,
    Simulation,
    bisect_failure,
    run_scenario,
)
from hyperqueue_tpu.sim.invariants import InvariantViolation
from hyperqueue_tpu.sim.loop import SimClock, SimDeadlockError, SimEventLoop
from hyperqueue_tpu.sim.workloads import WORKLOADS, Workload, build

__all__ = [
    "FaultEvent",
    "FaultSchedule",
    "InvariantViolation",
    "SimClock",
    "SimDeadlockError",
    "SimEventLoop",
    "SimResult",
    "Simulation",
    "WORKLOADS",
    "Workload",
    "bisect_failure",
    "build",
    "run_scenario",
]
