"""Per-job / per-label usage accounting ledger (ISSUE 18).

The server already journals every task-lifecycle transition with its
stamps (``queued_at``/``assigned_at``/``started_at`` on task-started,
the record clock on restart/terminal events). This module folds those
records into an incremental ledger of consumed resource-time — the
per-entity usage table fairness policies are computed over (Gavel,
arXiv:2008.09213) and the substrate quota/admission control needs
before it can be enforced (ROADMAP items 1 and 4).

Design rules:

- **Pure fold.** ``observe(kind, record)`` consumes the SAME record
  dict the journal persists, and is called from exactly three places:
  the live ``emit_event`` path, snapshot-tail/full replay
  (``events/restore.py``), and migration-record import. Live state and
  a kill -9 replay therefore produce bit-identical ledgers by
  construction — same records, same order, same float operations.
- **O(1) per event.** A run-span opens at task-started (resource
  amounts ride the record's ``usage`` field) and closes at the next
  restart/terminal record; closing charges ``duration x amount`` per
  resource. No per-tick walks, no timers.
- **Exactly-once across moves.** A migration record carries the
  source's accrued row (``export_job``); the destination seeds it from
  the journaled ``migration-in`` record (idempotent replace), and the
  source drops its copy only at the journaled ``migration-out-done``
  tombstone — the same discipline job state itself follows.
- **Reattach-safe.** A reattaching worker re-emits task-started with
  the SAME instance and the preserved original ``started_at``; the fold
  treats that as a refresh of the open span, never a second one.

Rows outlive ``job forget`` deliberately (forget is not journaled):
usage is an audit surface, not job state.
"""

from __future__ import annotations

# event kinds the fold consumes — exported so the hot emit path can
# skip record construction for irrelevant kinds when nobody else
# consumes events (sim servers without a journal)
ACCOUNTED_KINDS = frozenset((
    "job-submitted", "job-opened",
    "task-started", "task-restarted",
    "task-finished", "task-failed", "task-canceled",
    "migration-out", "migration-in", "migration-out-done",
))

_TERMINAL_STATUS = {
    "task-finished": "finished",
    "task-failed": "failed",
    "task-canceled": "canceled",
}

VERSION = 1


def _new_row(label: str) -> dict:
    return {
        "label": label,
        # wall-clock seconds of task execution (sum over run spans;
        # gang tasks count ONE task-second per wall second — resource
        # charges below carry the gang width)
        "task_seconds": 0.0,
        # ready -> running latency, charged once per dispatched span
        "wait_seconds": 0.0,
        # resource name -> amount x seconds (cpus/gpus/... in human
        # units; a 4-cpu task running 10 s charges 40 cpu-seconds)
        "resource_seconds": {},
        # crash-charged retries: increments of the task crash counter
        # (clean-stop restarts and migrations charge nothing)
        "crash_retries": 0,
        "runs": 0,
        "finished": 0,
        "failed": 0,
        "canceled": 0,
        # provenance flags for rollup transparency across moves
        "migrated_in": False,
        "migrating": False,
    }


class AccountingLedger:
    """Incremental per-job usage ledger; per-label rollups are derived
    at query time so a migrated row never double-counts its label."""

    def __init__(self):
        self.rows: dict[int, dict] = {}
        # (job, task) -> {"started", "instance", "usage"} for spans
        # currently running (task-started seen, no close yet)
        self.open_runs: dict[tuple[int, int], dict] = {}
        # (job, task) -> last crash_count folded, for delta charging
        self.last_crash: dict[tuple[int, int], int] = {}

    # ------------------------------------------------------------ fold
    def observe(self, kind: str, record: dict) -> None:
        if kind not in ACCOUNTED_KINDS:
            return
        job_id = record.get("job")
        if kind == "job-submitted":
            desc = record.get("desc") or {}
            row = self.rows.get(job_id)
            if row is None:
                self.rows[job_id] = _new_row(
                    str(desc.get("name", "job"))
                )
            return
        if kind == "job-opened":
            if job_id not in self.rows:
                self.rows[job_id] = _new_row(
                    str(record.get("name", "job"))
                )
            return
        if kind == "task-started":
            self._on_started(job_id, record)
            return
        if kind == "task-restarted":
            key = (job_id, record.get("task"))
            self._close_run(key, float(record.get("time", 0.0)))
            crash = int(record.get("crash_count", 0))
            last = self.last_crash.get(key, 0)
            if crash > last:
                self._row(job_id)["crash_retries"] += crash - last
                self.last_crash[key] = crash
            return
        status = _TERMINAL_STATUS.get(kind)
        if status is not None:
            key = (job_id, record.get("task"))
            self._close_run(key, float(record.get("time", 0.0)))
            self._row(job_id)[status] += 1
            self.last_crash.pop(key, None)
            return
        if kind == "migration-out":
            self._row(job_id)["migrating"] = True
            return
        if kind == "migration-in":
            self._on_migration_in(record.get("record") or {})
            return
        if kind == "migration-out-done":
            # tombstone: the destination owns the accrued usage now
            self.rows.pop(job_id, None)
            for table in (self.open_runs, self.last_crash):
                for key in [k for k in table if k[0] == job_id]:
                    del table[key]
            return

    def _row(self, job_id: int) -> dict:
        row = self.rows.get(job_id)
        if row is None:
            # task events for a job whose submit predates the journal
            # (rotated away) still accrue, under a placeholder label
            row = self.rows[job_id] = _new_row("job")
        return row

    def _on_started(self, job_id: int, record: dict) -> None:
        key = (job_id, record.get("task"))
        instance = int(record.get("instance", 0))
        started = float(record.get("started_at", 0.0)) or float(
            record.get("time", 0.0)
        )
        usage = record.get("usage") or {}
        run = self.open_runs.get(key)
        if run is not None and run["instance"] == instance:
            # reattach re-emit: one unbroken span — refresh the stamps
            # (started_at is preserved by the reattach path), never a
            # second wait charge or a second span
            run["started"] = started
            run["usage"] = dict(usage)
            return
        if run is not None:
            # a restart whose task-restarted record predates this
            # journal (defensive): close the stale span at its own
            # start so nothing is charged twice
            self._close_run(key, started)
        row = self._row(job_id)
        queued = float(record.get("queued_at", 0.0))
        if queued and started > queued:
            row["wait_seconds"] += started - queued
        self.open_runs[key] = {
            "started": started,
            "instance": instance,
            "usage": dict(usage),
        }

    def _close_run(self, key: tuple, end: float) -> None:
        run = self.open_runs.pop(key, None)
        if run is None:
            return
        row = self._row(key[0])
        duration = end - run["started"]
        if duration <= 0.0:
            return
        row["task_seconds"] += duration
        row["runs"] += 1
        resource_seconds = row["resource_seconds"]
        for name, amount in run["usage"].items():
            resource_seconds[name] = (
                resource_seconds.get(name, 0.0) + duration * amount
            )

    def _on_migration_in(self, rec: dict) -> None:
        jd = rec.get("job_state") or {}
        job_id = rec.get("job", jd.get("id"))
        if job_id is None:
            return
        acct = rec.get("accounting")
        if acct and acct.get("row"):
            # idempotent REPLACE: the exported row is the accrued truth;
            # a re-driven import lands on the same state
            row = dict(_new_row("job"), **acct["row"])
            row["resource_seconds"] = dict(
                row.get("resource_seconds") or {}
            )
            self.rows[job_id] = row
            for task_id, run in acct.get("open_runs") or ():
                self.open_runs[(job_id, task_id)] = dict(run)
            for task_id, crash in acct.get("last_crash") or ():
                self.last_crash[(job_id, task_id)] = int(crash)
        elif job_id not in self.rows:
            # pre-accounting migration record: start a fresh row
            self.rows[job_id] = _new_row(str(jd.get("name", "job")))
        row = self.rows[job_id]
        row["migrated_in"] = True
        row["migrating"] = False

    # ------------------------------------------------- snapshot capture
    def capture(self) -> dict:
        """Msgpack-safe full state for the journal snapshot (tuple keys
        become lists; ordering sorted so captures are deterministic)."""
        return {
            "version": VERSION,
            "rows": [
                [job_id, self._wire_row(self.rows[job_id])]
                for job_id in sorted(self.rows)
            ],
            "open_runs": [
                [list(key), dict(self.open_runs[key])]
                for key in sorted(self.open_runs)
            ],
            "last_crash": [
                [list(key), self.last_crash[key]]
                for key in sorted(self.last_crash)
            ],
        }

    @staticmethod
    def _wire_row(row: dict) -> dict:
        out = dict(row)
        out["resource_seconds"] = dict(row["resource_seconds"])
        return out

    def seed(self, state: dict | None) -> None:
        """Install a snapshot's captured ledger (None = pre-accounting
        snapshot: start empty; the journal tail refills what it can)."""
        self.rows = {}
        self.open_runs = {}
        self.last_crash = {}
        if not state:
            return
        for job_id, row in state.get("rows") or ():
            merged = dict(_new_row("job"), **row)
            merged["resource_seconds"] = dict(
                merged.get("resource_seconds") or {}
            )
            self.rows[int(job_id)] = merged
        for key, run in state.get("open_runs") or ():
            self.open_runs[(int(key[0]), int(key[1]))] = dict(run)
        for key, crash in state.get("last_crash") or ():
            self.last_crash[(int(key[0]), int(key[1]))] = int(crash)

    # ------------------------------------------------- migration export
    def export_job(self, job_id: int) -> dict:
        """Self-contained accrual for ONE job, embedded in a migration
        record so the destination seeds exactly what the source drops."""
        row = self.rows.get(job_id)
        return {
            "row": self._wire_row(row) if row is not None else None,
            "open_runs": [
                [key[1], dict(run)]
                for key, run in sorted(self.open_runs.items())
                if key[0] == job_id
            ],
            "last_crash": [
                [key[1], crash]
                for key, crash in sorted(self.last_crash.items())
                if key[0] == job_id
            ],
        }

    # ---------------------------------------------------------- queries
    def job_report(self, job_ids=None) -> dict[int, dict]:
        """Public per-job rows (derived cpu/gpu shorthand included),
        charged-to-now for open spans via ``now`` in rollup callers —
        deliberately NOT here: reports show only CLOSED charges, so a
        report is stable under replay at any instant."""
        if job_ids is None:
            job_ids = sorted(self.rows)
        out = {}
        running = {}
        for key in self.open_runs:
            running[key[0]] = running.get(key[0], 0) + 1
        for job_id in job_ids:
            row = self.rows.get(job_id)
            if row is None:
                continue
            out[job_id] = self._public_row(row, running.get(job_id, 0))
        return out

    @staticmethod
    def _public_row(row: dict, running: int) -> dict:
        resource_seconds = {
            name: round(secs, 6)
            for name, secs in sorted(row["resource_seconds"].items())
        }
        return {
            "label": row["label"],
            "task_seconds": round(row["task_seconds"], 6),
            "wait_seconds": round(row["wait_seconds"], 6),
            "cpu_seconds": resource_seconds.get("cpus", 0.0),
            "gpu_seconds": resource_seconds.get("gpus", 0.0),
            "resource_seconds": resource_seconds,
            "crash_retries": row["crash_retries"],
            "runs": row["runs"],
            "finished": row["finished"],
            "failed": row["failed"],
            "canceled": row["canceled"],
            "running": running,
            "migrated_in": row["migrated_in"],
            "migrating": row["migrating"],
        }

    def rollup(self) -> dict:
        """Per-label aggregation + grand totals (labels derived from job
        rows at query time: a migrated job contributes to exactly one
        shard's rollup because exactly one shard holds its row)."""
        labels: dict[str, dict] = {}
        totals = _agg_new()
        running = {}
        for key in self.open_runs:
            running[key[0]] = running.get(key[0], 0) + 1
        for job_id, row in self.rows.items():
            agg = labels.get(row["label"])
            if agg is None:
                agg = labels[row["label"]] = _agg_new()
            for target in (agg, totals):
                _agg_add(target, row, running.get(job_id, 0))
        return {
            "labels": {
                name: _agg_round(labels[name])
                for name in sorted(labels)
            },
            "totals": _agg_round(totals),
        }

    def brief(self) -> dict:
        """Tiny rollup for the subscribe-plane sample block / fleet
        feed: totals only, cheap enough to ride every sample."""
        rolled = self.rollup()["totals"]
        return {
            "jobs": rolled["jobs"],
            "task_seconds": rolled["task_seconds"],
            "cpu_seconds": rolled["cpu_seconds"],
            "gpu_seconds": rolled["gpu_seconds"],
            "wait_seconds": rolled["wait_seconds"],
            "crash_retries": rolled["crash_retries"],
            "running": rolled["running"],
        }


def _agg_new() -> dict:
    return {
        "jobs": 0, "task_seconds": 0.0, "wait_seconds": 0.0,
        "cpu_seconds": 0.0, "gpu_seconds": 0.0,
        "resource_seconds": {}, "crash_retries": 0, "runs": 0,
        "finished": 0, "failed": 0, "canceled": 0, "running": 0,
    }


def _agg_add(agg: dict, row: dict, running: int) -> None:
    agg["jobs"] += 1
    agg["task_seconds"] += row["task_seconds"]
    agg["wait_seconds"] += row["wait_seconds"]
    agg["crash_retries"] += row["crash_retries"]
    agg["runs"] += row["runs"]
    agg["finished"] += row["finished"]
    agg["failed"] += row["failed"]
    agg["canceled"] += row["canceled"]
    agg["running"] += running
    resource_seconds = agg["resource_seconds"]
    for name, secs in row["resource_seconds"].items():
        resource_seconds[name] = resource_seconds.get(name, 0.0) + secs
    agg["cpu_seconds"] = resource_seconds.get("cpus", 0.0)
    agg["gpu_seconds"] = resource_seconds.get("gpus", 0.0)


def _agg_round(agg: dict) -> dict:
    out = dict(agg)
    for field in ("task_seconds", "wait_seconds", "cpu_seconds",
                  "gpu_seconds"):
        out[field] = round(out[field], 6)
    out["resource_seconds"] = {
        name: round(secs, 6)
        for name, secs in sorted(agg["resource_seconds"].items())
    }
    return out
