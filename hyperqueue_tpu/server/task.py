"""Server-side task state machine.

Reference: crates/tako/src/internal/server/task.rs:22-43 —
Waiting{unfinished_deps} -> Assigned -> Running -> Finished, with instance ids
(restart counter, task.rs) so stale messages from a previous incarnation are
discarded, and crash counters driving the CrashLimit policy
(reference gateway.rs:96-106).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class TaskState(enum.Enum):
    WAITING = "waiting"     # has unfinished dependencies
    READY = "ready"         # in a scheduler queue
    ASSIGNED = "assigned"   # compute message sent to a worker
    RUNNING = "running"     # worker reported start
    FINISHED = "finished"
    FAILED = "failed"
    CANCELED = "canceled"


TERMINAL_STATES = (TaskState.FINISHED, TaskState.FAILED, TaskState.CANCELED)

DEFAULT_CRASH_LIMIT = 5  # reference gateway.rs: MaxCrashes(5)

# Restart fencing: boot g (g = prior server-uid records in the journal)
# re-issues every restored non-terminal task at instance >= g * STRIDE.
# A crashed boot can have issued SEVERAL instances of one task whose
# lifecycle events all died in its unflushed journal tail (start,
# worker-lost requeue, restart — each bumps by 1), so fencing by "+1 past
# what the journal saw" can collide with a lost incarnation that still
# runs on a reconnecting worker. The stride clears everything a prior
# boot could have issued as long as no single boot bumps one task more
# than STRIDE times — requeues are bounded by the crash limit, orders of
# magnitude below 2^20.
INSTANCE_GENERATION_STRIDE = 1 << 20


@dataclass(slots=True)
class Task:
    task_id: int
    rq_id: int
    priority: tuple[int, int] = (0, 0)
    body: dict = field(default_factory=dict)
    # array-entry payload (HQ_ENTRY), kept OUT of body so every task of an
    # entries array shares one body object — the wire layer dedups shared
    # bodies per compute message (reference messages/worker.rs:28-54
    # shared/separate data split)
    entry: str | None = None
    deps: tuple[int, ...] = ()
    crash_limit: int = DEFAULT_CRASH_LIMIT

    state: TaskState = TaskState.WAITING
    unfinished_deps: int = 0
    consumers: set[int] = field(default_factory=set)
    instance_id: int = 0
    crash_counter: int = 0
    assigned_worker: int = 0  # 0 = none
    assigned_variant: int = 0
    # assigned beyond current capacity: queued on the worker, resources not
    # yet accounted (reference mapping.rs proactive prefilling)
    prefilled: bool = False
    # a retract request is in flight; don't re-send every tick while the
    # worker's answer travels back
    retract_pending: bool = False
    # multi-node gangs: workers allocated to this task (root first)
    mn_workers: tuple[int, ...] = ()

    # lifecycle timeline (wall-clock, 0 = not reached for the CURRENT
    # incarnation): became ready / assigned to a worker / worker reported
    # running. Feed `hq job timeline` + the task-started event payload;
    # cleared by increment_instance so every restart starts a fresh chain.
    t_ready: float = 0.0
    t_assigned: float = 0.0
    t_started: float = 0.0

    @property
    def is_done(self) -> bool:
        return self.state in TERMINAL_STATES

    def increment_instance(self) -> int:
        self.instance_id += 1
        # a new incarnation gets a fresh lifecycle chain; the timeline of
        # the dead one already lives in the journal/job records
        self.t_ready = 0.0
        self.t_assigned = 0.0
        self.t_started = 0.0
        return self.instance_id

    def fence_instance(self, floor: int) -> int:
        """Advance the instance past every incarnation a crashed boot
        could have issued: always by at least 1, and at least to `floor`
        (the restoring boot's generation base, Core.instance_fence_floor).
        Used wherever a restored task is re-issued instead of reattached —
        a bump-by-one there could collide with an incarnation whose
        lifecycle events died in the crashed boot's unflushed tail."""
        self.instance_id = max(self.instance_id + 1, floor)
        self.t_ready = 0.0
        self.t_assigned = 0.0
        self.t_started = 0.0
        return self.instance_id

    @property
    def never_restart(self) -> bool:
        """crash_limit encodes CRASH_LIMIT_NEVER_RESTART (utils/parsing.py):
        fail whenever the worker is lost while the task runs, even on clean
        stops (reference CrashLimit::NeverRestart, reactor.rs:166 — outside
        the reason.is_failure() gate)."""
        return self.crash_limit < 0

    def crashed(self) -> bool:
        """Register a crash (worker lost while running); True if over limit."""
        self.crash_counter += 1
        return self.crash_limit > 0 and self.crash_counter >= self.crash_limit
